"""Cost-oracle properties (tpu_reductions/exec/cost.py — ISSUE 19):
monotone regime flips on each axis, the empty-evidence degradation to
the static picks, the exec.select audit row, the report fold, and the
drift gate over the committed decision artifact
(examples/tpu_run/exec_decisions.json)."""

import json
from pathlib import Path

import pytest

from tpu_reductions.exec.cost import (CostOracle, decisions_markdown,
                                      emit_select)

REPO = Path(__file__).resolve().parent.parent
ARTIFACT = REPO / "examples" / "tpu_run" / "exec_decisions.json"


@pytest.fixture()
def oracle():
    """The oracle over the repo's own committed evidence — exactly
    what the CLIs see when run from the checkout root."""
    return CostOracle(root=str(REPO))


@pytest.fixture()
def empty_oracle(tmp_path):
    return CostOracle(root=str(tmp_path))


# ------------------------------------------------- empty-evidence floor

def test_empty_evidence_degrades_every_axis_to_the_static_pick(
        empty_oracle):
    k = empty_oracle.pick_kernel("SUM", "int", 1 << 28)
    assert (k.choice, k.static_choice, k.flipped) == ("k6", "k6", False)
    assert k.evidence == ()
    t = empty_oracle.pick_topology(64, 3 * 64)
    assert (t.choice, t.flipped) == ("ring", False)
    assert t.evidence == ()
    w = empty_oracle.pick_wire("SUM", "float32", 8, 1 << 24, None)
    assert (w.choice, w.flipped) == ("exact", False)
    s = empty_oracle.pick_scan("float32", 1 << 24)
    assert (s.choice, s.flipped) == ("xla-cumsum", False)
    assert s.evidence == ()


# -------------------------------------------------------- the scan axis

def test_scan_pick_is_float_only_and_priced_from_family_evidence(
        oracle, empty_oracle):
    d = oracle.pick_scan("int32", 1 << 24)
    assert d.choice == "xla-cumsum" and not d.flipped
    assert "float-only" in d.reason
    assert [n for n, _ in d.candidates] == ["xla-cumsum"]
    # same guard with no evidence at all
    assert empty_oracle.pick_scan("int32", 1 << 24).choice == "xla-cumsum"
    d = oracle.pick_scan("float32", 1 << 26)
    assert d.choice in ("xla-cumsum", "mxu-scan")
    if d.evidence:   # committed family_spot present: both cands priced
        assert any("family_spot" in e for e in d.evidence)
        assert all(s is not None for _, s in d.candidates)
        best = min(d.candidates, key=lambda c: c[1])[0]
        assert d.choice == best


# ----------------------------------------------------- monotone regimes

def test_kernel_pick_is_monotone_in_n_and_flips_at_the_residency_bound(
        oracle):
    choices = [oracle.pick_kernel("SUM", "int", 1 << e).choice
               for e in range(20, 29)]
    assert choices[0] == "k6" and choices[-1] == "k10"
    # one crossover, never back: every k10 is after every k6
    assert choices == sorted(choices, key=lambda c: c == "k10")


def test_kernel_flip_reason_names_the_regime(oracle):
    small = oracle.pick_kernel("SUM", "int", 1 << 22)
    big = oracle.pick_kernel("SUM", "int", 1 << 28)
    assert "<=" in small.reason and not small.flipped
    assert big.flipped and "deep-DMA overlap" in big.reason
    assert big.evidence            # the artifacts the pick consulted
    assert all(s is not None for _, s in big.candidates)


def test_topology_pick_is_monotone_in_k(oracle):
    choices = [oracle.pick_topology(k, 3 * k).choice
               for k in (2, 4, 16, 64)]
    assert choices[0] == "ring" and choices[-1] == "torus2d"
    flipped = [c != "ring" for c in choices]
    assert flipped == sorted(flipped)   # once off ring, never back


def test_wire_pick_is_monotone_in_slack(oracle):
    choices = [oracle.pick_wire("SUM", "float32", 8, 1 << 24, s).choice
               for s in (10.0, 1.0, 0.01, 0.001)]
    assert choices[0] == "exact" and choices[-1] == "q8"
    quant = [c != "exact" for c in choices]
    assert quant == sorted(quant)       # shrinking slack: exact -> q8


def test_wire_pick_never_quantizes_unsupported_combos(oracle):
    d = oracle.pick_wire("MIN", "float32", 8, 1 << 24, 1e-6)
    assert d.choice == "exact" and "not quantizable" in d.reason
    d = oracle.pick_wire("SUM", "double", 8, 1 << 24, 1e-6)
    assert d.choice == "exact"


# -------------------------------------------------------- the audit row

def test_decision_row_shape_and_select_event(tmp_path, monkeypatch,
                                             oracle):
    from tpu_reductions.obs import ledger
    led = tmp_path / "l.jsonl"
    monkeypatch.setenv("TPU_REDUCTIONS_LEDGER", str(led))
    ledger.arm(led)
    try:
        d = oracle.pick_kernel("SUM", "int", 1 << 28)
        emit_select(d, method="SUM", dtype="int", n=1 << 28)
    finally:
        ledger.disarm()
    row = d.row()
    assert row["axis"] == "kernel" and row["flipped"] is True
    assert row["static"] == "k6"
    assert {c["name"] for c in row["candidates"]} == {"k6", "k10"}
    ev = json.loads(led.read_text().splitlines()[-1])
    assert ev["ev"] == "exec.select"
    assert ev["choice"] == row["choice"] and ev["n"] == 1 << 28


def test_decisions_markdown_counts_flips_and_skips_empty():
    assert decisions_markdown({"rows": []}) == ""
    doc = {"rows": [
        {"axis": "kernel", "choice": "k10", "static": "k6",
         "flipped": True, "reason": "HBM", "geometry": {"n": 1}},
        {"axis": "wire", "choice": "exact", "static": "exact",
         "flipped": False, "reason": "no deadline", "geometry": {}},
    ]}
    md = decisions_markdown(doc)
    assert "| kernel | n=1 | k10 | k6 | YES | HBM |" in md
    assert "2 decision(s), 1 regime flip(s)" in md


# ----------------------------------------------------------- drift gate

def test_committed_decision_artifact_matches_the_oracle(oracle):
    """The committed exec_decisions.json IS the oracle's output over
    the committed evidence: a selector or evidence change that moves
    any pick must show up as an artifact diff in review, never as a
    silent behavior change (regenerate with `python -m
    tpu_reductions.exec --explain --platform=cpu
    --out=examples/tpu_run/exec_decisions.json`)."""
    from tpu_reductions.exec.__main__ import decision_rows
    doc = json.loads(ARTIFACT.read_text())
    assert doc["complete"] is True
    assert doc["rows"] == decision_rows(oracle)


def test_committed_artifact_shows_a_flip_on_every_axis():
    """ISSUE 19 acceptance: the cost oracle demonstrably flips at
    least 3 picks with regime, visible in the committed artifact."""
    doc = json.loads(ARTIFACT.read_text())
    flipped_axes = {r["axis"] for r in doc["rows"] if r["flipped"]}
    # the scan axis (ISSUE 20) flips only if the committed family-spot
    # rates put mxu-scan ahead — evidence-dependent, so not required
    assert {"kernel", "topology", "wire"} <= flipped_axes
