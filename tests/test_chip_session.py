"""Chip-session step machinery rehearsal (scripts/chip_session.sh in
CHIP_SESSION_LIB mode): the commit-per-step, per-step budget, and
abort-on-rc-3 contracts are what a live window depends on — a bash bug
there must be found off-chip, not mid-window (round-3 verdict, weak
#2/#3)."""

import subprocess
from pathlib import Path

SCRIPT = Path(__file__).resolve().parent.parent / "scripts/chip_session.sh"


def _drive(tmp_path, body):
    """Source the step machinery into a fresh throwaway git repo and run
    `body` (bash) there. relay_ok is overridden to pass: these tests
    rehearse the step contract, not the probe."""
    repo = tmp_path / "repo"
    repo.mkdir()
    script = (
        "set -u\n"
        f"export CHIP_SESSION_LIB=1\n"
        f"source '{SCRIPT}'\n"
        f"cd '{repo}'\n"
        "git init -q . && git config user.email t@t && git config user.name t\n"
        "git commit -q --allow-empty -m root\n"
        "relay_ok() { return 0; }\n" + body)
    return repo, subprocess.run(["bash", "-c", script],
                                capture_output=True, text=True,
                                timeout=120)


def _log(repo):
    return subprocess.run(["git", "-C", str(repo), "log", "--oneline"],
                          capture_output=True, text=True).stdout


def test_step_commits_only_its_artifact(tmp_path):
    repo, r = _drive(tmp_path,
                     "echo stray > untracked.txt\n"
                     "step 'toy pass' 30 art.json -- "
                     "bash -c 'echo data > art.json'\n")
    assert r.returncode == 0, r.stdout + r.stderr
    log = _log(repo)
    assert "On-chip artifacts: toy pass" in log
    # the stray file must NOT be swept into the artifact commit
    show = subprocess.run(["git", "-C", str(repo), "show",
                           "--stat", "--name-only", "HEAD"],
                          capture_output=True, text=True).stdout
    assert "art.json" in show and "untracked.txt" not in show


def test_failed_step_commits_partial_artifacts_and_continues(tmp_path):
    repo, r = _drive(tmp_path,
                     "step 'toy fail' 30 part.json -- "
                     "bash -c 'echo partial > part.json; exit 1'\n"
                     "step 'after' 30 after.json -- "
                     "bash -c 'echo ok > after.json'\n")
    assert r.returncode == 0, r.stdout + r.stderr
    assert "toy fail FAILED rc=1" in r.stdout
    log = _log(repo)
    assert "toy fail (step FAILED; partial artifacts)" in log
    assert "On-chip artifacts: after" in log      # session continued


def test_step_budget_times_out_and_continues(tmp_path):
    """A slow-but-alive step is cut at its budget (SIGINT via timeout)
    and whatever it persisted before the cut is committed; the NEXT
    step still runs — the round-3 weak-#2 contract."""
    repo, r = _drive(tmp_path,
                     "step 'toy stall' 1 stall.json -- "
                     "bash -c 'echo early > stall.json; sleep 30'\n"
                     "step 'after' 30 after.json -- "
                     "bash -c 'echo ok > after.json'\n")
    assert r.returncode == 0, r.stdout + r.stderr
    assert "toy stall TIMED OUT after 1s" in r.stdout
    log = _log(repo)
    assert "toy stall (step FAILED; partial artifacts)" in log
    assert "On-chip artifacts: after" in log


def test_step_rc3_aborts_session_with_artifacts_committed(tmp_path):
    repo, r = _drive(tmp_path,
                     "step 'toy outage' 30 out.json -- "
                     "bash -c 'echo partial > out.json; exit 3'\n"
                     "step 'never' 30 never.json -- "
                     "bash -c 'echo no > never.json'\n")
    assert r.returncode == 3
    assert "accelerator gone (rc=3)" in r.stdout
    log = _log(repo)
    assert "toy outage" in log
    assert "never" not in log
    assert not (repo / "never.json").exists()


def test_dead_relay_between_steps_aborts(tmp_path):
    repo, r = _drive(tmp_path,
                     "step 'first' 30 a.json -- bash -c 'echo 1 > a.json'\n"
                     "relay_ok() { return 3; }\n"
                     "step 'second' 30 b.json -- bash -c 'echo 2 > b.json'\n")
    assert r.returncode == 3
    assert "relay died before step 'second'" in r.stdout
    assert "On-chip artifacts: first" in _log(repo)


def test_summarize_on_exit_requires_a_step_and_commits_summary(tmp_path):
    """The EXIT trap's guard: an abort BEFORE any step ran must not
    collate stale artifacts into a 'window summary'; after a step ran,
    the summary is written and committed even though the session is
    exiting."""
    import json

    summarizer = SCRIPT.parent / "summarize_window.py"
    bench_row = json.dumps({"metric": "m", "value": 6497.2,
                            "unit": "GB/s", "vs_baseline": 71.5})
    body = (
        "mkdir -p scripts\n"
        f"cp '{summarizer}' scripts/\n"
        f"printf '%s' '{bench_row}' > BENCH_live.json\n"
        # no step ran: the trap must be a no-op
        "summarize_on_exit\n"
        "test ! -e WINDOW_SUMMARY.md || exit 97\n"
        # a step runs; now the trap collates and commits
        "step 'toy' 30 art.json -- bash -c 'echo d > art.json'\n"
        "summarize_on_exit\n")
    repo, r = _drive(tmp_path, body)
    assert r.returncode == 0, r.stdout + r.stderr
    assert (repo / "WINDOW_SUMMARY.md").is_file()
    assert "6497.2" in (repo / "WINDOW_SUMMARY.md").read_text()
    assert "Window summary (auto-collated at session exit)" in _log(repo)


def _fallback_body():
    text = SCRIPT.read_text()
    start = text.index("fallback_static_session()")
    # the function body ends at the next unindented closing brace
    return text[start:text.index("\n}", start)]


def test_session_is_scheduler_driven_with_static_fallback():
    """The round-5 tentpole, pinned: the live path routes every step
    through the scheduler (--next/--record against sched_state.json),
    and the hand-ordered list survives ONLY as the no-scheduler
    fallback — guarded so a mid-plan scheduler failure can never
    re-measure completed tasks by falling back."""
    text = SCRIPT.read_text()
    assert "run_scheduled_session" in text
    assert "tpu_reductions.sched --next --emit=shell" in text
    assert "tpu_reductions.sched --record" in text
    # the scheduler loop's step call takes the PLANNED budget, never a
    # literal
    assert 'step "$SCHED_TASK_NAME" "$SCHED_TASK_BUDGET"' in text
    assert "fallback_static_session" in text
    assert '"$SCHED_TASKS_RUN" -gt 0' in text   # mid-plan guard
    # a hang (exit 4) must stop the loop, not re-pick the hung task
    assert "STEP_LAST_RC" in text and "exit 4" in text


def test_fallback_budgets_keep_the_first_steps_inside_a_short_window():
    """The round-3 weak-#2 contract, pinned on the FALLBACK list (the
    scheduler's budgets live in sched/tasks.py and are pinned by
    tests/test_sched.py): every fallback step carries a numeric
    wall-clock budget, the first four sum inside a short window, and
    every budget carries its RED013 waiver (the sanctioned exception)."""
    import re

    body = _fallback_body().replace("\\\n", " ")
    budgets = [int(m.group(1)) for m in
               re.finditer(r"^\s*step ['\"][^'\"]+['\"] (\d+) ",
                           body, re.M)]
    steps = len(re.findall(r"^\s*step ['\"]", body, re.M))
    assert len(budgets) == steps, "a fallback step is missing its budget"
    assert len(budgets) >= 10          # the full value-ordered session
    assert sum(budgets[:4]) <= 18 * 60, (
        f"first four budgets sum to {sum(budgets[:4])}s — a short "
        "window is no longer guaranteed the first row + BENCH row + "
        "DOUBLE scoreboard + trust gate")
    # the flagship long tail must still be bounded (watcher re-arm
    # depends on the session eventually exiting)
    assert max(budgets) <= 4 * 3600
    assert body.count("redlint: disable=RED013") == steps


def test_fallback_budgets_mirror_the_scheduler_registry():
    """The fallback list and sched/tasks.py must not drift: same step
    titles, same budgets, same order as the registry's static fields."""
    import re

    from tpu_reductions.sched.tasks import SESSION_TASKS

    body = _fallback_body().replace("\\\n", " ")
    pairs = [(m.group(1), int(m.group(2))) for m in
             re.finditer(r"^\s*step ['\"]([^'\"]+)['\"] (\d+) ",
                         body, re.M)]
    expected = [(t.title, int(t.budget_s)) for t in SESSION_TASKS]
    assert pairs == expected


def test_session_step0_is_firstrow_with_t0_export():
    """Round-4 verdict do-this #3, pinned: firstrow is the top
    value-per-second pick of a fresh plan (sched/tasks.py) AND the
    fallback's first step, with FIRSTROW_T0 exported before the
    scheduler loop so the committed timeline measures from 'relay
    answered', not from python's first line."""
    from tpu_reductions.sched.priors import Priors
    from tpu_reductions.sched.tasks import SESSION_TASKS

    pri = Priors()
    ratios = {t.name: t.value / pri.estimate(t) for t in SESSION_TASKS}
    assert max(ratios, key=ratios.get) == "firstrow"

    text = SCRIPT.read_text()
    body = _fallback_body()
    assert body.index('step "first row"') == body.index('step "'), (
        "firstrow must be the fallback's first step")
    assert text.index("FIRSTROW_T0=$(date") \
        < text.index("run_scheduled_session && sched_rc")
    assert "tpu_reductions.bench.firstrow" in text
    # the headline bench must not re-measure a scoreboard firstrow
    # completed (both the registry command and the fallback carry it)
    assert "BENCH_DOUBLES=$d" in text


def test_doubles_suppression_requires_a_verified_row():
    """An all-FAILED/WAIVED step-0 scoreboard (e.g. a flap mid-dd-
    compile) must NOT suppress step 1's fresh doubles attempt (round-5
    ADVICE): the BENCH_DOUBLES=0 branch demands a PASSED row in
    BENCH_doubles.json alongside completeness and same-session mtime."""
    text = SCRIPT.read_text()
    cond = text[text.index('step "headline bench"'):]
    cond = cond[:cond.index("python bench.py")]
    assert '\\"complete\\": true' in cond
    assert '\\"status\\": \\"PASSED\\"' in cond
    assert "FIRSTROW_T0" in cond


def _flagship_row():
    import json
    return json.dumps({
        "method": "SUM", "dtype": "float64", "n": 1 << 24,
        "backend": "pallas", "kernel": 6, "gbps": 150.0, "avg_s": 1e-3,
        "iterations": 256, "status": "PASSED", "device_result": 1.0,
        "oracle_result": 1.0, "abs_diff": 0.0, "waived_reason": None,
        "timing": "chained", "threads": 512, "max_blocks": 64,
        "chain_reps": 5})


def test_exit_trap_collates_evidence_committed_by_a_step(tmp_path):
    """The round-4 bridge, end to end in the step harness: a step
    commits fresh flagship cells itself (the step-11 shape the
    dirty-worktree test alone would miss); the exit trap must notice
    the moved examples/tpu_run head, regenerate the report offline,
    and commit it — and a second trap run with nothing new must NOT
    commit again."""
    import os

    repo_root = str(SCRIPT.parent.parent)
    raw = "examples/tpu_run/single_chip/raw_output"
    body = (
        f"export PYTHONPATH='{repo_root}'\n"
        # pre-session flagship state, committed (the round-2 analog)
        f"mkdir -p {raw}\n"
        f"printf '%s' '{_flagship_row()}' > {raw}/run-float64-SUM-0.json\n"
        "git add examples && git commit -q -m pre-session\n"
        # the session: one step that writes AND commits a new cell
        # (artifact = the directory, exactly like the flagship step)
        "step 'toy flagship' 60 examples/tpu_run -- "
        "bash -c 'echo \"[]\" > examples/tpu_run/shmoo.json'\n"
        "summarize_on_exit\n"
        "echo TRAP_DONE\n"
        "summarize_on_exit\n"   # idempotency: nothing new now
        "echo TRAP2_DONE\n")
    repo, r = _drive(tmp_path, body)
    assert "TRAP2_DONE" in r.stdout, r.stdout + r.stderr
    log = _log(repo)
    assert "On-chip artifacts: toy flagship" in log
    assert log.count("Window evidence collated") == 1, log
    # the regen really ran: report artifacts exist in the temp repo
    assert (repo / "examples/tpu_run/report.md").is_file()
    md = (repo / "examples/tpu_run/report.md").read_text()
    assert "150.0" in md


def _toy_sched_tasks(repo):
    import json

    (repo / "toy_tasks.json").write_text(json.dumps([
        {"name": "alpha", "title": "toy alpha", "value": 10,
         "budget_s": 30,
         "command": "printf '{\"complete\": true}' > a.json",
         "artifacts": ["a.json"], "done_artifact": "a.json"},
        {"name": "beta", "title": "toy beta", "value": 5, "budget_s": 30,
         "command": "printf '{\"complete\": true}' > b.json",
         "artifacts": ["b.json"], "done_artifact": "b.json"},
    ]))


def test_scheduler_loop_drives_steps_and_commits_plan_state(tmp_path):
    """The tentpole acceptance for the shell side: chip_session's
    scheduler loop pulls picks from `python -m tpu_reductions.sched
    --next`, runs each through the SAME step machinery (per-step
    commits), records outcomes, and ends with the plan complete and
    sched_state.json committed alongside the artifacts."""
    import json

    repo_root = str(SCRIPT.parent.parent)
    body = (
        f"export PYTHONPATH='{repo_root}'\n"
        "export TPU_REDUCTIONS_SCHED_ARGS='--tasks=toy_tasks.json'\n"
        "SCHED_ARGS=$TPU_REDUCTIONS_SCHED_ARGS\n"
        "run_scheduled_session; echo LOOP_RC=$?\n")
    repo = tmp_path / "repo"
    repo.mkdir()
    _toy_sched_tasks(repo)
    script = (
        "set -u\n"
        "export CHIP_SESSION_LIB=1\n"
        f"source '{SCRIPT}'\n"
        f"cd '{repo}'\n"
        "git init -q . && git config user.email t@t && git config user.name t\n"
        "git commit -q --allow-empty -m root\n"
        "relay_ok() { return 0; }\n" + body)
    r = subprocess.run(["bash", "-c", script], capture_output=True,
                       text=True, timeout=120)
    assert "LOOP_RC=0" in r.stdout, r.stdout + r.stderr
    log = _log(repo)
    assert "On-chip artifacts: toy alpha" in log
    assert "On-chip artifacts: toy beta" in log
    state = json.loads((repo / "sched_state.json").read_text())
    assert state["complete"] is True
    assert state["tasks"]["alpha"]["status"] == "done"
    assert state["tasks"]["beta"]["status"] == "done"
    # the plan state is committed per step like the ledger is
    show = subprocess.run(["git", "-C", str(repo), "log",
                           "--name-only", "--oneline"],
                          capture_output=True, text=True).stdout
    assert "sched_state.json" in show


def test_scheduler_loop_rc3_aborts_and_plan_resumes(tmp_path):
    """Window-death handoff in the shell loop: a task exiting 3 aborts
    the session via step() (artifacts + plan state committed); the
    NEXT session invocation resumes the plan and runs only the
    remaining task."""
    import json

    repo_root = str(SCRIPT.parent.parent)
    repo = tmp_path / "repo"
    repo.mkdir()
    (repo / "toy_tasks.json").write_text(json.dumps([
        {"name": "alpha", "title": "toy alpha", "value": 10,
         "budget_s": 30,
         "command": "echo r >> a.runs; printf '{\"complete\": true}' "
                    "> a.json",
         "artifacts": ["a.json"], "done_artifact": "a.json"},
        {"name": "dies", "title": "toy dies", "value": 5, "budget_s": 30,
         "command": "[ -e window2 ] || exit 3; "
                    "printf '{\"complete\": true}' > d.json",
         "artifacts": ["d.json"], "done_artifact": "d.json"},
    ]))
    script = (
        "set -u\n"
        "export CHIP_SESSION_LIB=1\n"
        f"source '{SCRIPT}'\n"
        f"cd '{repo}'\n"
        "git init -q . && git config user.email t@t && git config user.name t\n"
        "git commit -q --allow-empty -m root\n"
        "relay_ok() { return 0; }\n"
        f"export PYTHONPATH='{repo_root}'\n"
        "SCHED_ARGS='--tasks=toy_tasks.json'\n"
        "( run_scheduled_session ); echo WINDOW1_RC=$?\n"
        "touch window2\n"
        "( run_scheduled_session ); echo WINDOW2_RC=$?\n")
    r = subprocess.run(["bash", "-c", script], capture_output=True,
                       text=True, timeout=120)
    assert "WINDOW1_RC=3" in r.stdout, r.stdout + r.stderr
    assert "WINDOW2_RC=0" in r.stdout, r.stdout + r.stderr
    state = json.loads((repo / "sched_state.json").read_text())
    assert state["complete"] is True
    # alpha ran exactly once across both windows (zero re-measurement)
    assert (repo / "a.runs").read_text().count("r") == 1


def test_exit_trap_skips_collation_when_nothing_changed(tmp_path):
    repo_root = str(SCRIPT.parent.parent)
    body = (
        f"export PYTHONPATH='{repo_root}'\n"
        "mkdir -p examples/tpu_run\n"
        "echo x > examples/tpu_run/marker.txt\n"
        "git add examples && git commit -q -m pre-session\n"
        "step 'toy' 30 art.json -- bash -c 'echo d > art.json'\n"
        "summarize_on_exit\n")
    repo, r = _drive(tmp_path, body)
    assert r.returncode == 0, r.stdout + r.stderr
    assert "Window evidence collated" not in _log(repo)


def test_step_commits_compile_ledger_alongside_artifacts(tmp_path):
    """ISSUE 8 satellite: when TPU_REDUCTIONS_COMPILE_LEDGER names the
    observatory artifact, step() commits it with the step's artifacts
    (the same ride-along contract the flight-recorder ledger has), and
    the exit trap copies it next to the flagship evidence."""
    repo, r = _drive(
        tmp_path,
        "export TPU_REDUCTIONS_COMPILE_LEDGER=compile_ledger.json\n"
        "mkdir -p examples/tpu_run\n"
        "step 'toy compile' 30 art.json -- bash -c "
        "'echo data > art.json; "
        "echo \"{\\\"kind\\\": \\\"compile-observatory\\\"}\" "
        "> compile_ledger.json'\n"
        "SESSION_RAN=1\n"
        "summarize_on_exit\n")
    assert r.returncode == 0, r.stdout + r.stderr
    show = subprocess.run(["git", "-C", str(repo), "show",
                           "--name-only", "HEAD", "--oneline"],
                          capture_output=True, text=True).stdout
    # committed with the step (whichever commit it landed in, it must
    # be tracked)
    tracked = subprocess.run(["git", "-C", str(repo), "ls-files"],
                             capture_output=True, text=True).stdout
    assert "compile_ledger.json" in tracked, show
    # the exit trap copied it next to the evidence for the regen fold
    assert (repo / "examples/tpu_run/compile_ledger.json").exists()
