"""Unified checkpoint/resume (bench/resume.py): every --out-writing
entry point (spot, autotune, smoke, calibrate, firstrow, sweep) is
idempotent under re-invocation — rows persisted by an interrupted run
are reused, not re-measured, and a COMPLETE artifact re-measures fresh
(per-window freshness contract)."""

import json

import pytest

from tpu_reductions.bench.resume import (Checkpoint, default_reusable,
                                         load_cell, prior_artifact,
                                         store_cell)

# stable_chained_timing (tests/conftest.py) keeps CLI-shape runs from
# flaking WAIVED on a loaded host where a PASSED row is asserted


# ------------------------------------------------------------- Checkpoint


def test_checkpoint_persists_incrementally_and_finalizes(tmp_path):
    out = tmp_path / "a.json"
    ck = Checkpoint(out, {"n": 64}, key_fn=lambda r: r["k"])
    ck.add({"k": "x", "status": "PASSED"})
    snap = json.loads(out.read_text())
    assert snap["complete"] is False and snap["n"] == 64
    assert [r["k"] for r in snap["rows"]] == ["x"]
    ck.finalize(extra={"best": "x"})
    snap = json.loads(out.read_text())
    assert snap["complete"] is True and snap["best"] == "x"


def test_checkpoint_resumes_only_from_incomplete_matching_meta(tmp_path):
    out = tmp_path / "a.json"
    ck = Checkpoint(out, {"n": 64}, key_fn=lambda r: r["k"])
    row = {"k": "x", "status": "PASSED", "gbps": 9.9}
    ck.add(row)
    # interrupted (complete=false) + same meta -> resumed, unmutated
    ck2 = Checkpoint(out, {"n": 64}, key_fn=lambda r: r["k"])
    assert ck2.resume("x") == row
    assert ck2.reused == ["x"]
    assert ck2.resume("y") is None
    # different meta -> a different campaign: nothing resumes
    ck3 = Checkpoint(out, {"n": 128}, key_fn=lambda r: r["k"])
    assert ck3.resume("x") is None
    # completed artifact -> fresh campaign by contract
    ck2.add(row)
    ck2.finalize()
    ck4 = Checkpoint(out, {"n": 64}, key_fn=lambda r: r["k"])
    assert ck4.resume("x") is None
    # ...unless the caller opts in (sweep-style cell semantics)
    ck5 = Checkpoint(out, {"n": 64}, key_fn=lambda r: r["k"],
                     resume_from_complete=True)
    assert ck5.resume("x") == row


def test_checkpoint_failed_rows_are_not_reusable(tmp_path):
    out = tmp_path / "a.json"
    ck = Checkpoint(out, {}, key_fn=lambda r: r["k"])
    ck.add({"k": "bad", "status": "FAILED"})
    ck.add({"k": "ok", "status": "WAIVED"})
    ck2 = Checkpoint(out, {}, key_fn=lambda r: r["k"])
    assert ck2.resume("bad") is None     # failures re-measure
    assert ck2.resume("ok") is not None  # by-design waivers reuse


def test_checkpoint_no_path_is_in_memory_only():
    ck = Checkpoint(None, {"n": 1}, key_fn=lambda r: r["k"])
    ck.add({"k": "x"})
    ck.finalize()
    assert ck.rows == [{"k": "x"}]


def test_checkpoint_sort_key_orders_every_persist(tmp_path):
    out = tmp_path / "ranked.json"
    ck = Checkpoint(out, {}, rows_key="ranked",
                    key_fn=lambda r: r["k"],
                    sort_key=lambda r: -r["gbps"])
    ck.add({"k": "slow", "gbps": 1.0})
    ck.add({"k": "fast", "gbps": 9.0})
    snap = json.loads(out.read_text())
    assert [r["k"] for r in snap["ranked"]] == ["fast", "slow"]


def test_checkpoint_truncated_prior_is_ignored(tmp_path):
    out = tmp_path / "a.json"
    out.write_text('{"complete": false, "rows": [{"tru')
    ck = Checkpoint(out, {}, key_fn=lambda r: r["k"])
    assert ck.resume("anything") is None


def test_prior_artifact_contract(tmp_path):
    out = tmp_path / "one.json"
    out.write_text(json.dumps({"n": 7, "complete": False,
                               "row": {"status": "PASSED"}}))
    assert prior_artifact(out, {"n": 7})["row"]["status"] == "PASSED"
    assert prior_artifact(out, {"n": 8}) is None
    out.write_text(json.dumps({"n": 7, "complete": True, "row": {}}))
    assert prior_artifact(out, {"n": 7}) is None
    assert prior_artifact(tmp_path / "absent.json", {}) is None


def test_default_reusable_accepts_smoke_ok_rows():
    assert default_reusable({"ok": True, "status": "PASSED"})
    assert not default_reusable({"ok": False, "status": "FAILED"})
    assert not default_reusable({"no": "verdict"})


def test_store_and_load_cell_roundtrip_and_truncation(tmp_path):
    cell = tmp_path / "run-int32-SUM-0.json"
    store_cell(cell, {"status": "PASSED", "gbps": 5.0})
    assert load_cell(cell)["gbps"] == 5.0
    assert cell.read_text().endswith("\n")   # one-line cache format
    cell.write_text('{"status": "PA')        # pre-atomic truncation
    assert load_cell(cell) == {}             # caller re-measures
    assert load_cell(tmp_path / "absent.json") == {}


# ------------------------------------------- entry-point idempotency
#
# Pattern per entry point: run once, mark the artifact interrupted
# (complete=false — what a watchdog exit-3 mid-run leaves behind),
# re-invoke with the benchmark core counting its calls: persisted rows
# must be reused (zero re-measures), missing rows measured fresh, and
# the final artifact complete.


def _interrupt(path):
    data = json.loads(path.read_text())
    data["complete"] = False
    path.write_text(json.dumps(data))
    return data


def _count_run_benchmark(monkeypatch):
    from tpu_reductions.bench import driver as drv
    real = drv.run_benchmark
    calls = []

    def counting(cfg, **kw):
        calls.append((cfg.method, cfg.dtype, getattr(cfg, "kernel", None),
                      getattr(cfg, "threads", None)))
        return real(cfg, **kw)

    monkeypatch.setattr(drv, "run_benchmark", counting)
    return calls


def test_spot_reinvocation_skips_persisted_rows(tmp_path, monkeypatch,
                                                stable_chained_timing):
    from tpu_reductions.bench.spot import main
    out = tmp_path / "spot.json"
    argv = ["--type=int", "--n=16384", "--iterations=8", "--chainreps=2",
            f"--out={out}"]
    assert main(["--methods=SUM"] + argv) == 0
    before = json.loads(out.read_text())["rows"]
    _interrupt(out)

    calls = _count_run_benchmark(monkeypatch)
    assert main(["--methods=SUM,MIN,MAX"] + argv) == 0
    data = json.loads(out.read_text())
    assert data["complete"] is True
    assert [r["method"] for r in data["rows"]] == ["SUM", "MIN", "MAX"]
    assert [c[0] for c in calls] == ["MIN", "MAX"]   # SUM resumed
    assert data["rows"][0] == before[0]              # byte-identical row


def test_spot_complete_artifact_remeasures_fresh(tmp_path, monkeypatch,
                                                 stable_chained_timing):
    """A finished scoreboard re-invoked is a NEW campaign (per-window
    freshness): every method re-measures."""
    from tpu_reductions.bench.spot import main
    out = tmp_path / "spot.json"
    argv = ["--methods=SUM,MIN", "--type=int", "--n=16384",
            "--iterations=8", "--chainreps=2", f"--out={out}"]
    assert main(argv) == 0
    calls = _count_run_benchmark(monkeypatch)
    assert main(argv) == 0
    assert [c[0] for c in calls] == ["SUM", "MIN"]


def test_smoke_reinvocation_skips_persisted_cases(tmp_path, monkeypatch):
    from tpu_reductions.bench import smoke as smoke_mod
    from tpu_reductions.bench.resume import Checkpoint

    # a prior interrupted manifest holding the first two cases
    out = tmp_path / "smoke.json"
    names = ([c[0] for c in smoke_mod.CASES]
             + [c[0] for c in smoke_mod.FAMILY_CASES])
    ck = Checkpoint(out, {"n": 1 << 20}, rows_key="cases",
                    key_fn=lambda r: r["name"])
    banked = [{"name": n, "status": "PASSED", "ok": True,
               "seconds": 1.0, "error": None} for n in names[:2]]
    for r in banked:
        ck.add(r)
    # counting fake core: the resumed cases must never reach it
    from tpu_reductions.bench import driver as drv
    from tpu_reductions.utils.qa import QAStatus
    ran = []

    class _Res:
        status = QAStatus.PASSED

    monkeypatch.setattr(drv, "run_benchmark",
                        lambda cfg, **kw: ran.append(cfg.method) or _Res())
    rc = smoke_mod.main([f"--out={out}", "--platform=cpu"])
    assert rc == 0
    data = json.loads(out.read_text())
    assert data["complete"] is True
    assert [c["name"] for c in data["cases"]] == names
    assert data["cases"][:2] == banked          # reused, unmutated
    # only the missing CLASSIC cases reach the benchmark core (the
    # family cases lower through their own jits, not run_benchmark)
    assert len(ran) == len(smoke_mod.CASES) - 2


def test_autotune_reinvocation_skips_persisted_candidates(
        tmp_path, monkeypatch, stable_chained_timing):
    from tpu_reductions.bench import autotune as at
    tiny = ((6, 16, 64), (6, 32, 64), (7, 16, 32))
    monkeypatch.setitem(at.GRIDS, "fine", tiny)
    out = tmp_path / "tune.json"
    argv = ["--method=SUM", "--type=int", "--n=4096", "--iterations=4",
            "--chainreps=2", "--grid=fine", f"--out={out}"]
    assert at.main(argv) == 0
    first = json.loads(out.read_text())
    _interrupt(out)

    calls = _count_run_benchmark(monkeypatch)
    assert at.main(argv) == 0
    data = json.loads(out.read_text())
    assert data["complete"] is True and data["best"] is not None
    assert calls == []                        # every candidate resumed
    assert len(data["ranked"]) == len(tiny)
    assert data["ranked"] == first["ranked"]  # identical row set


def test_calibrate_ladder_resumes_measured_rungs(tmp_path, monkeypatch):
    from tpu_reductions.utils import calibrate as cal_mod
    out = tmp_path / "cal.json"
    argv = ["--platform=cpu", "--n=16384", "--iters=2", "--reps=2",
            "--chainspan=8", "--ladder", f"--out={out}"]

    real = cal_mod.calibrate
    calls = []

    def wrapped(**kw):
        calls.append(kw["n"])
        if len(calls) == 2:
            raise RuntimeError("injected relay death between rungs")
        return real(**kw)

    monkeypatch.setattr(cal_mod, "calibrate", wrapped)
    with pytest.raises(RuntimeError):
        cal_mod.main(argv)
    snap = json.loads(out.read_text())
    assert snap["complete"] is False and len(snap["rungs"]) == 1

    calls.clear()
    monkeypatch.setattr(cal_mod, "calibrate",
                        lambda **kw: calls.append(kw["n"]) or real(**kw))
    assert cal_mod.main(argv) == 0
    data = json.loads(out.read_text())
    assert data["complete"] is True and len(data["rungs"]) == 2
    assert calls == [16384 * 4]              # VMEM rung resumed
    assert data["rungs"][0] == snap["rungs"][0]


def test_firstrow_reinvocation_reuses_verified_row(tmp_path, monkeypatch,
                                                   stable_chained_timing):
    from tpu_reductions.bench import firstrow
    out = tmp_path / "FIRSTROW.json"
    argv = ["--platform=cpu", "--n=16384", "--iterations=8",
            "--chainreps=2", "--skip-doubles", f"--out={out}"]
    assert firstrow.main(argv) == 0
    before = json.loads(out.read_text())
    _interrupt(out)

    calls = _count_run_benchmark(monkeypatch)
    assert firstrow.main(argv) == 0
    data = json.loads(out.read_text())
    assert data["complete"] is True
    assert calls == []                       # the int row was reused
    assert data["row"] == before["row"]
    assert any("resumed" in m["label"] for m in data["timeline"])


def test_sweep_cells_resume_via_shared_store(tmp_path,
                                             stable_chained_timing):
    """sweep_all's per-cell cache now rides bench/resume.store_cell /
    load_cell — an interrupted grid keeps its verified cells and a
    re-invocation reloads them instead of re-measuring (cell-grain,
    complete runs included: the 3-h flagship contract)."""
    from tpu_reductions.bench.sweep import sweep_all
    rows = sweep_all(methods=("SUM",), dtypes=("int32",), n=4096,
                     repeats=2, iterations=4, timing="chained",
                     chain_reps=2, out_dir=str(tmp_path))
    raw = sorted((tmp_path / "raw_output").glob("run-*.json"))
    assert len(raw) == sum(1 for r in rows if r["status"] == "PASSED")
    if not raw:
        pytest.skip("no PASSED cells at toy scale on this host")
    first = load_cell(raw[0])
    rows2 = sweep_all(methods=("SUM",), dtypes=("int32",), n=4096,
                      repeats=2, iterations=4, timing="chained",
                      chain_reps=2, out_dir=str(tmp_path))
    # resumed rows carry the SAME measurement (gbps identical — a
    # re-measure could not reproduce the float exactly)
    resumed = [r for r in rows2 if r["repeat"] == first["repeat"]
               and r["status"] == "PASSED"]
    assert resumed and resumed[0]["gbps"] == first["gbps"]
