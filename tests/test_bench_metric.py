"""bench.py — the round metric — smoke-tested off-chip. The metric path
has to survive refactors between on-chip opportunities; these tests run
its full candidate race on the CPU backend and pin the outage fallback's
shape (a bad metric file is worse than a bad kernel: it silently
misreports the whole round)."""

import importlib.util
import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load_bench():
    spec = importlib.util.spec_from_file_location(
        "bench_under_test", os.path.join(REPO, "bench.py"))
    m = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(m)
    return m


def test_bench_main_cpu_smoke(capsys):
    bench = _load_bench()
    rc = bench.main(["--n", "65536", "--iterations", "16",
                     "--platform", "cpu"])
    assert rc == 0
    out = capsys.readouterr().out.strip().splitlines()[-1]
    d = json.loads(out)
    assert d["unit"] == "GB/s"
    assert d["value"] > 0
    assert d["metric"].endswith("n=2^16")
    assert d["vs_baseline"] == round(d["value"] / bench.BASELINE_GBPS, 4)


def test_bench_outage_fallback_surfaces_snapshot(capsys, monkeypatch):
    bench = _load_bench()
    monkeypatch.setattr(bench, "_device_probe",
                        lambda platform=None: "fake wedge")
    rc = bench.main([])
    assert rc == 1          # outage is never a clean exit
    out = capsys.readouterr().out.strip().splitlines()[-1]
    d = json.loads(out)
    # the committed mid-round verified snapshot, clearly labeled stale
    assert d["stale"] is True
    assert d["value"] > 0
    assert "not a fresh run" in d["note"]
    assert d["source"] == "BENCH_r02_snapshot.json"


def test_bench_outage_without_snapshot_reports_zero(tmp_path):
    bench = _load_bench()
    # a missing snapshot file -> honest 0.0, never a crash
    d = bench._snapshot_fallback("fake wedge",
                                 snap=str(tmp_path / "missing.json"))
    assert d["value"] == 0.0 and d["vs_baseline"] == 0.0
    # a malformed snapshot (null value) degrades the same way
    bad = tmp_path / "bad.json"
    bad.write_text('{"value": null}')
    d2 = bench._snapshot_fallback("fake wedge", snap=str(bad))
    assert d2["value"] == 0.0


def test_bench_rejects_nonpositive_n():
    import pytest
    bench = _load_bench()
    with pytest.raises(SystemExit):
        bench.main(["--n", "0", "--platform", "cpu"])


def test_committed_snapshot_is_valid_for_round_end_fallback():
    """The driver's end-of-round bench run falls back to the COMMITTED
    BENCH_r02_snapshot.json when the accelerator is unavailable — a
    hand-edit that breaks that file would silently turn the round
    metric into 0.0. Pin it: strict JSON, the schema the fallback
    reads, and a verified-positive value."""
    bench = _load_bench()
    snap_path = os.path.join(REPO, "BENCH_r02_snapshot.json")
    raw = json.loads(open(snap_path).read())   # strict parse
    assert raw["value"] > 0 and raw["unit"] == "GB/s"
    assert "captured" in raw and "provenance" in raw

    d = bench._snapshot_fallback("test outage")   # default = committed
    assert d["stale"] is True
    assert d["value"] == raw["value"] > 0
    assert d["vs_baseline"] == round(raw["value"] / bench.BASELINE_GBPS, 4)
    assert d["source"] == "BENCH_r02_snapshot.json"


def test_bench_double_spots_best_effort(tmp_path, capsys, monkeypatch,
                                        stable_chained_timing):
    """The opportunistic DOUBLE scoreboard (VERDICT r2 item 1): f64
    SUM/MIN/MAX rows land in BENCH_doubles.json via the dd path, rows
    persist as they land, stdout stays untouched (the one-JSON-line
    contract), and BENCH_DOUBLES=0 disables it."""
    import json

    import bench

    out = tmp_path / "BENCH_doubles.json"
    monkeypatch.delenv("BENCH_DOUBLES", raising=False)
    bench._maybe_double_spots(n=1 << 14, iterations=8, reps=2,
                              path=str(out))
    data = json.loads(out.read_text())
    assert data["complete"] is True
    assert [r["method"] for r in data["rows"]] == ["SUM", "MIN", "MAX"]
    assert all(r["status"] == "PASSED" for r in data["rows"])
    assert data["reference"]["SUM"] == 92.7729
    assert capsys.readouterr().out == ""   # stderr only

    out2 = tmp_path / "off.json"
    monkeypatch.setenv("BENCH_DOUBLES", "0")
    bench._maybe_double_spots(n=1 << 14, iterations=8, reps=2,
                              path=str(out2))
    assert not out2.exists()


def test_bench_double_spots_swallows_failures(tmp_path, monkeypatch):
    """Best-effort contract: a doubles crash must not propagate (the
    headline exit code is already decided when this runs)."""
    import bench
    from tpu_reductions.bench import spot as spot_mod

    def boom(*a, **kw):
        raise RuntimeError("synthetic dd failure")

    monkeypatch.setattr(spot_mod, "run_spots", boom)
    bench._maybe_double_spots(n=1 << 14, iterations=8, reps=2,
                              path=str(tmp_path / "x.json"))  # no raise


def test_bench_persists_incrementally_on_flagship_geometry(monkeypatch,
                                                           capsys):
    """Round-4 window lesson: the relay FLAPS — a ~6-minute window died
    between bench.py's dispatch and its first persisted artifact. On
    flagship geometry main() must therefore (a) write a partial
    snapshot the moment the first candidate verifies, (b) fire the
    doubles scoreboard right after candidate 0 (the verdict's #1 gap
    must not wait behind the runner-ups), and (c) finish with a
    complete (non-partial) snapshot."""
    import bench

    calls = []
    monkeypatch.setattr(
        bench, "_write_snapshot",
        lambda payload, prov: calls.append(("snap", dict(payload),
                                            len(prov))))
    monkeypatch.setattr(
        bench, "_maybe_double_spots",
        lambda *a, **kw: calls.append(("doubles",)))
    monkeypatch.setattr(bench, "_on_flagship_geometry", lambda n: True)

    rc = bench.main(["--n", "65536", "--iterations", "16",
                     "--platform", "cpu"])
    assert rc == 0
    out = capsys.readouterr().out.strip().splitlines()[-1]
    assert json.loads(out)["value"] > 0   # headline contract untouched

    kinds = [c[0] for c in calls]
    assert kinds.count("doubles") == 1
    # doubles fire after candidate 0's snapshot, before any runner-up's
    assert kinds.index("doubles") <= 1
    snaps = [c for c in calls if c[0] == "snap"]
    assert len(snaps) == len(bench.CANDIDATES)   # one per candidate
    assert snaps[0][1].get("partial") is True    # mid-race = partial
    assert snaps[0][2] == 1                      # provenance so far
    assert "partial" not in snaps[-1][1]         # final = complete
    assert snaps[-1][2] == len(bench.CANDIDATES)
    assert snaps[-1][1]["value"] >= snaps[0][1]["value"]


def test_bench_skip_probe_env(monkeypatch, capsys):
    """BENCH_SKIP_PROBE=1 (set by chip_session.sh, which verified the
    relay seconds earlier) must skip the ~30-40 s device-probe
    subprocess entirely — the probe would re-pay a full jax init out
    of a window that may only be minutes long."""
    bench = _load_bench()

    def boom(platform=None):
        raise AssertionError("probe ran despite BENCH_SKIP_PROBE=1")

    monkeypatch.setattr(bench, "_device_probe", boom)
    monkeypatch.setenv("BENCH_SKIP_PROBE", "1")
    # no --platform: exactly the flagship invocation shape (conftest
    # has already pinned the backend to cpu for the test process)
    rc = bench.main(["--n", "65536", "--iterations", "8"])
    assert rc == 0
    out = capsys.readouterr().out.strip().splitlines()[-1]
    assert json.loads(out)["value"] > 0


def test_bench_notes_headline_upset_by_runner_up(monkeypatch, capsys):
    """Round-4 ADVICE 1: on flagship geometry the single stdout line
    prints as soon as the first candidate verifies; if a runner-up
    later wins the race, a corrective stderr note must say so and name
    BENCH_snapshot.json as authoritative (the printed line itself is
    immutable — downstream tooling already consumed it)."""
    import dataclasses

    import bench
    from tpu_reductions.bench import driver as drv
    from tpu_reductions.utils.qa import QAStatus

    monkeypatch.setattr(bench, "_write_snapshot", lambda *a, **kw: None)
    monkeypatch.setattr(bench, "_maybe_double_spots", lambda *a, **kw: None)
    monkeypatch.setattr(bench, "_on_flagship_geometry", lambda n: True)

    rates = iter([100.0, 250.0, 90.0, 80.0])   # runner-up upsets leader

    def fake_batch(cfgs, logger=None, **kw):
        cfg = cfgs[0]
        return [drv.BenchResult(cfg.method, cfg.dtype, cfg.n, cfg.backend,
                                cfg.kernel, next(rates), 1e-3,
                                cfg.iterations, QAStatus.PASSED,
                                1.0, 1.0, 0.0, timing="chained")]

    monkeypatch.setattr(bench, "run_benchmark_batch", fake_batch,
                        raising=False)
    import tpu_reductions.bench.driver as driver_mod
    monkeypatch.setattr(driver_mod, "run_benchmark_batch", fake_batch)

    rc = bench.main(["--n", "65536", "--iterations", "16",
                     "--platform", "cpu"])
    assert rc == 0
    cap = capsys.readouterr()
    headline = json.loads(cap.out.strip().splitlines()[-1])
    assert headline["value"] == 100.0        # printed at first verify
    assert "BENCH_snapshot.json is" in cap.err.replace("\n", " ")
    assert "250.0" in cap.err
