"""Lowering smoke (bench/smoke.py): the pre-race manifest that converts
a systematic Mosaic lowering failure from a burned window-middle into a
seconds-cost line in the session log (round-3 verdict, weak #3)."""

import json

from tpu_reductions.bench.smoke import (CASES, FAMILY_CASES, main,
                                        run_smoke)


def test_run_smoke_covers_every_never_lowered_surface():
    seen = []
    rows = run_smoke(on_result=lambda r: seen.append(r["name"]))
    want = [c[0] for c in CASES] + [c[0] for c in FAMILY_CASES]
    assert [r["name"] for r in rows] == want
    assert seen == want                         # fired per case, in order
    # on the virtual-CPU platform every surface lowers and verifies
    assert all(r["ok"] and r["status"] in ("PASSED", "WAIVED")
               for r in rows)
    # the k10 depth knob and both dd pair paths are distinct cases
    names = " ".join(seen)
    for frag in ("depth=2", "depth=4", "depth=8", "mxu f32", "mxu bf16",
                 "big-tile", "sum pair-tree", "min key-pair",
                 "mxu-scan", "cumsum", "seg reduce", "argk"):
        assert frag in names


def test_run_smoke_contains_a_crashing_case(monkeypatch):
    """One kernel that cannot lower must record FAILED with the error
    string and leave the other cases' rows intact — the manifest is the
    product; a crash is the information the step buys."""
    from tpu_reductions.bench import driver as drv

    real = drv.run_benchmark

    def sabotaged(cfg, **kw):
        if cfg.kernel == 9:
            raise RuntimeError("synthetic Mosaic lowering failure")
        return real(cfg, **kw)

    monkeypatch.setattr(drv, "run_benchmark", sabotaged)
    rows = run_smoke()
    by = {r["name"]: r for r in rows}
    assert not by["k9 mxu f32"]["ok"]
    assert "synthetic Mosaic" in by["k9 mxu f32"]["error"]
    assert by["k10 stream depth=4"]["ok"]
    assert by["dd f64 sum pair-tree"]["ok"]


def test_smoke_cli_writes_manifest(tmp_path, capsys):
    out = tmp_path / "smoke.json"
    assert main([f"--out={out}"]) == 0
    data = json.loads(out.read_text())
    assert data["complete"] is True
    total = len(CASES) + len(FAMILY_CASES)
    assert len(data["cases"]) == total
    assert (f"{total}/{total} cases lowered and verified"
            in capsys.readouterr().out)


def test_smoke_cli_rejects_too_small_n():
    import pytest
    with pytest.raises(SystemExit):
        main(["--n=1024"])
