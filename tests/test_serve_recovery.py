"""Crash-consistent control plane coverage (ISSUE 18;
serve/journal.py + serve/router.adopt_fleet + the engine's
exactly-once dedup cache): the fleet journal's atomic round-trip and
meta contract, write-ahead ordering (the record lands on disk BEFORE
the action it describes), the dedup cache's exactly-once property
under interleaved retries and its bounded-eviction at-least-once
fallback (an evicted key re-executes, never hangs), the settlement
vocabulary (transport/lifecycle failures stay retryable), adoption's
stale/dead verdicts, the autoscaler's mid-cooldown export/restore,
the timeline's crash-recovery attribution, the ledger-joined
exactly-once audit, and the chaos e2e: a REAL journaled router
subprocess killed mid-burst by the scripted `router.crash` os._exit,
restarted against the same journal — replica children re-ADOPTED (not
respawned), retried keys answered with ZERO duplicate device
executions, zero orphans after teardown."""

import json
import os
import random
import signal
import socket
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

from tpu_reductions.obs.timeline import recovery_summary, summarize, \
    summary_markdown
from tpu_reductions.ops import oracle
from tpu_reductions.serve.autoscale import Autoscaler
from tpu_reductions.serve.engine import ServeEngine
from tpu_reductions.serve.journal import (FleetJournal, JOURNAL_META,
                                          REPLICA_STATES)
from tpu_reductions.serve.loadgen import (_recovery_client,
                                          _recovery_evidence,
                                          _stamp_idem, plan_workload,
                                          recovery_markdown)
from tpu_reductions.serve.request import ReduceRequest
from tpu_reductions.serve.router import adopt_fleet


class FakeExecutor:
    """Deterministic device stand-in (same as tests/test_serve_scale):
    resolves with the payload's real oracle value, no jax."""

    def __init__(self, hold=None):
        self.hold = hold              # threading.Event: block until set
        self.launches = []

    def capabilities(self):
        return {"backend": "cpu", "supports_f64": True,
                "device_count": 1}

    def run_batch(self, method, dtype, n, seeds):
        self.launches.append((method, dtype, n, tuple(seeds)))
        if self.hold is not None:
            assert self.hold.wait(timeout=30)
        out = []
        from tpu_reductions.utils.rng import host_data
        for s in seeds:
            host = oracle.host_reduce(host_data(n, dtype, seed=s),
                                      method)
            v = float(np.asarray(host, dtype=np.float64))
            out.append({"result": v, "ok": True, "host": v,
                        "diff": 0.0})
        return out


def _engine(**kw):
    kw.setdefault("executor", FakeExecutor())
    kw.setdefault("coalesce_window_s", 0.0)
    return ServeEngine(**kw)


def _req(key, seed, method="SUM", n=64):
    return ReduceRequest(method=method, dtype="int32", n=n, seed=seed,
                         idem_key=key)


# ------------------------------------------------------ fleet journal


def test_journal_round_trip_and_meta_contract(tmp_path):
    """A journal reloads byte-faithfully (replicas + placements +
    autoscaler state) under the meta contract; a foreign/mismatched
    meta is refused — an empty fleet record, never someone else's."""
    path = str(tmp_path / "fleet_journal.json")
    j = FleetJournal(path)
    j.record_replica("replica-0", state="up", port=4242, pid=777,
                     platform="cpu")
    j.record_replica("replica-1", state="starting")
    j.record_placement("SUM", "int32", 4096)
    j.record_placement("SUM", "int32", 4096)   # deduped
    j.record_autoscaler({"last_action_wall": 123.0, "calm": 2,
                         "next_idx": 3})
    j2 = FleetJournal(path)
    assert j2.replicas() == j.replicas()
    assert j2.placements() == [("SUM", "int32", 4096)]
    assert j2.autoscaler_state()["calm"] == 2
    # meta contract: a version bump makes it some other instrument's
    # file — replay refuses rather than adopting a fleet it does not
    # describe
    data = json.loads(open(path).read())
    data["version"] = JOURNAL_META["version"] + 1
    from tpu_reductions.utils.jsonio import atomic_json_dump
    atomic_json_dump(path, data)
    j3 = FleetJournal(path)
    assert j3.replicas() == {}
    assert j3.placements() == []
    assert j3.autoscaler_state() is None


def test_journal_write_ahead_and_field_preservation(tmp_path):
    """Every record is on disk the moment the call returns (the
    write-AHEAD half of the contract: the journal never claims less
    than reality), and a later transition keeps previously-journaled
    fields it does not restate — a drain does not forget the port the
    adoption probe needs."""
    path = str(tmp_path / "j.json")
    j = FleetJournal(path)
    j.record_replica("replica-0", state="starting")
    on_disk = json.loads(open(path).read())
    assert on_disk["replicas"]["replica-0"]["state"] == "starting"
    j.record_replica("replica-0", state="up", port=5151, pid=999)
    j.record_replica("replica-0", state="draining")
    entry = json.loads(open(path).read())["replicas"]["replica-0"]
    assert entry == {"state": "draining", "port": 5151, "pid": 999}
    j.forget_replica("replica-0")
    assert json.loads(open(path).read())["replicas"] == {}
    with pytest.raises(ValueError):
        j.record_replica("replica-0", state="exploded")
    assert "exploded" not in REPLICA_STATES


def test_journal_in_memory_without_path(tmp_path):
    """path=None keeps the whole record in memory — the in-process
    test fleets' shape: same call sites, zero disk writes."""
    j = FleetJournal(None)
    j.record_replica("replica-0", state="up", port=1, pid=2)
    j.record_placement("MIN", "float32", 128)
    assert j.replicas()["replica-0"]["port"] == 1
    assert j.placements() == [("MIN", "float32", 128)]
    assert list(tmp_path.iterdir()) == []


# ------------------------------------------- exactly-once dedup cache


def test_dedup_exactly_once_under_interleaved_retries():
    """The property: any interleaving of settled-then-retried keys
    settles each key to exactly ONE response value and exactly ONE
    device execution — every duplicate of a settled key answers from
    the cache without a launch."""
    ex = FakeExecutor()
    eng = _engine(executor=ex).start()
    try:
        rng = random.Random(11)
        keys = [f"k{i}" for i in range(8)]
        seeds = {k: 1000 + i for i, k in enumerate(keys)}
        schedule = [k for k in keys for _ in range(3)]
        rng.shuffle(schedule)
        responses = {k: [] for k in keys}
        for k in schedule:
            r = eng.submit(_req(k, seeds[k])).result(timeout=30)
            assert r.status == "ok", (r.status, r.error)
            responses[k].append(r)
        for k in keys:
            assert len({r.result for r in responses[k]}) == 1
        launched = [s for (_m, _d, _n, ss) in ex.launches for s in ss]
        for k in keys:
            assert launched.count(seeds[k]) == 1
        assert eng.stats["dedup_hits"] == len(schedule) - len(keys)
    finally:
        eng.stop()


def test_dedup_concurrent_duplicates_first_settle_wins():
    """Duplicates racing BEFORE settlement both execute (the cache
    only answers settled keys) but agree on the value; once settled,
    the cached response is pinned — a later duplicate returns the
    first settler's response without a new launch."""
    hold = threading.Event()
    ex = FakeExecutor(hold=hold)
    eng = _engine(executor=ex).start()
    try:
        p1 = eng.submit(_req("race", 42))
        p2 = eng.submit(_req("race", 42))
        hold.set()
        r1, r2 = p1.result(timeout=30), p2.result(timeout=30)
        assert r1.status == r2.status == "ok"
        assert r1.result == r2.result
        n_launches = len(ex.launches)
        r3 = eng.submit(_req("race", 42)).result(timeout=30)
        assert r3.status == "ok" and r3.result == r1.result
        assert len(ex.launches) == n_launches   # answered from cache
        assert eng.stats["dedup_hits"] == 1
    finally:
        eng.stop()


def test_dedup_bounded_eviction_at_least_once_never_hangs():
    """The documented at-least-once fallback: past the LRU bound an
    evicted key re-executes (one more launch, a correct response,
    never a hang); a still-cached key keeps answering without one."""
    ex = FakeExecutor()
    eng = _engine(executor=ex, dedup_cache_size=2).start()
    try:
        for i, k in enumerate(("a", "b", "c")):
            assert eng.submit(_req(k, 100 + i)) \
                .result(timeout=30).status == "ok"
        n_launches = len(ex.launches)
        # "c" is hot: cached, no launch
        assert eng.submit(_req("c", 102)).result(timeout=30) \
            .status == "ok"
        assert len(ex.launches) == n_launches
        # "a" was LRU-evicted by "c": re-executes, still resolves
        r = eng.submit(_req("a", 100)).result(timeout=30)
        assert r.status == "ok"
        assert len(ex.launches) == n_launches + 1
    finally:
        eng.stop()


def test_dedup_settlement_vocabulary():
    """What caches: ok always; an executed-and-failed error yes; a
    transport/lifecycle failure never (a cached one would poison every
    later retry of the key)."""
    settled = ServeEngine._dedup_settled
    assert settled("ok", None)
    assert settled("error", "verification failed: diff=1.0")
    assert not settled("error", "relay dead on every probe port")
    assert not settled("error", "replica-dead: relay-dead")
    assert not settled("error", "engine-stopped")
    assert not settled("error", "replica-draining")
    assert not settled("rejected", "queue full (depth 64)")
    assert not settled("expired", None)
    assert not settled("shed", None)


# ------------------------------------------------------- adoption


def test_adopt_fleet_stale_and_dead_verdicts(tmp_path):
    """The recovery probe's non-live verdicts: a write-ahead
    "starting" entry with no port is STALE (nothing to probe), a
    journaled pid that no longer exists is GONE — both are forgotten
    from the journal, neither is adopted."""
    path = str(tmp_path / "j.json")
    j = FleetJournal(path)
    j.record_replica("replica-0", state="starting")
    proc = subprocess.Popen([sys.executable, "-c", "pass"])
    proc.wait(timeout=30)
    # a bound-then-closed socket yields a port nothing listens on
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    dead_port = s.getsockname()[1]
    s.close()
    j.record_replica("replica-1", state="up", port=dead_port,
                     pid=proc.pid)
    adopted, reaped = adopt_fleet(j, reap_grace_s=0.2)
    assert adopted == []
    assert reaped == ["replica-1"]
    assert j.replicas() == {}
    assert FleetJournal(path).replicas() == {}


# ----------------------------------------- autoscaler cooldown resume


def test_autoscaler_cooldown_survives_restore():
    """export_state carries the cooldown anchor across processes as a
    WALL clock; restore converts the elapsed share back onto the
    successor's clock — a restart mid-cooldown stays cooling instead
    of re-firing the predecessor's decision."""
    class StubRouter:
        replicas = []
        journal = None

    clock = [100.0]
    a1 = Autoscaler(StubRouter(), spawn=lambda i: None,
                    cooldown_s=60.0, clock=lambda: clock[0])
    a1._last_action_t = clock[0]
    a1._last_action = "up"
    a1._calm = 2
    a1._next_idx = 5
    state = a1.export_state()
    assert state["cooldown_s"] == 60.0 and state["calm"] == 2
    a2 = Autoscaler(StubRouter(), spawn=lambda i: None,
                    cooldown_s=60.0, clock=lambda: clock[0])
    a2.restore_state(state)
    # the anchor restored onto a2's clock: elapsed ~0, so the full
    # cooldown remains
    assert a2._last_action_t is not None
    assert clock[0] - a2._last_action_t < 5.0
    assert a2._calm == 2 and a2._next_idx >= 5
    # empty state is a no-op (a journal with no autoscaler record)
    a3 = Autoscaler(StubRouter(), spawn=lambda i: None,
                    cooldown_s=60.0)
    a3.restore_state(None)
    assert a3._last_action_t is None


# -------------------------------------------- timeline / ledger joins


def test_timeline_recovery_summary_and_markdown():
    events = [
        {"ev": "session.start", "prog": "serve.router"},
        {"ev": "journal.record", "kind": "replica-up",
         "name": "replica-0", "replicas": 1},
        {"ev": "journal.record", "kind": "placement", "replicas": 1},
        {"ev": "journal.replay", "path": "j.json", "replicas": 2,
         "placements": 1, "autoscaler": True},
        {"ev": "adopt.begin", "candidates": 3},
        {"ev": "adopt.replica", "replica": "replica-0",
         "verdict": "adopted", "port": 1, "pid": 2},
        {"ev": "adopt.replica", "replica": "replica-1",
         "verdict": "adopted", "port": 3, "pid": 4},
        {"ev": "adopt.replica", "replica": "replica-2",
         "verdict": "gone", "port": 5, "pid": 6},
        {"ev": "adopt.done", "adopted": 2, "reaped": 1,
         "wall_s": 0.42},
        {"ev": "serve.dedup", "req": "r000001", "idem": "k0",
         "orig": "r000000", "status": "ok"},
    ]
    for i, e in enumerate(events):       # the ledger's line shape
        e.setdefault("t", 100.0 + 0.01 * i)
        e.setdefault("pid", 1)
    rec = recovery_summary(events)
    assert rec["recoveries"] == 1
    assert rec["adopted"] == 2 and rec["reaped"] == 1
    assert rec["verdicts"] == {"adopted": 2, "gone": 1}
    assert rec["journal_records"] == 2
    assert rec["journal_replays"] == 1
    assert rec["dedup_hits"] == 1
    assert rec["mttr_max_s"] == 0.42
    assert recovery_summary([{"ev": "serve.coalesce"}]) is None
    summary = summarize("x.jsonl", events, torn=0)
    md = summary_markdown(summary)
    assert "crash recovery" in md
    assert "0.42" in md


def test_recovery_evidence_joins_on_idem_keys(tmp_path):
    """The exactly-once audit counts coalesce-stamped idempotency
    keys (request ids are per-engine and collide across replicas) —
    per-key launches beyond the first are the duplicates; rotation
    sidecars are read oldest-first; other prefixes are invisible."""
    path = str(tmp_path / "ledger.jsonl")
    with open(path + ".1", "w") as f:     # rotated older half
        f.write(json.dumps({"ev": "serve.coalesce", "batch": 0,
                            "idems": ["kr-0", "kr-1"]}) + "\n")
    rows = [
        {"ev": "serve.coalesce", "batch": 1, "idems": ["kr-1", "x-9"]},
        {"ev": "serve.dedup", "idem": "kr-2"},
        {"ev": "serve.dedup", "idem": "x-2"},
        {"ev": "adopt.done", "adopted": 2, "reaped": 0,
         "wall_s": 0.3},
        "not json\n",
    ]
    with open(path, "w") as f:
        for r in rows:
            f.write(r if isinstance(r, str) else json.dumps(r) + "\n")
    ev = _recovery_evidence(path, "kr-")
    assert ev["executed_keys"] == 2
    assert ev["duplicates"] == 1          # kr-1 launched twice
    assert ev["dedup_hits"] == 1          # x-2 filtered out
    assert ev["adopted"] == 2 and ev["adopt_wall_s"] == 0.3
    empty = _recovery_evidence(str(tmp_path / "missing.jsonl"), "kr-")
    assert empty == {"duplicates": 0, "dedup_hits": 0,
                     "executed_keys": 0}


def test_recovery_markdown_orders_scenarios_and_flags_duplicates():
    art = {"dtype": "int", "methods": ["SUM"], "requests": 8,
           "crash_after": 3, "seed": 0, "platform": "cpu",
           "rows": [
               {"key": "drain", "requests": 8, "ok": 8,
                "shed": 0, "duplicates": 0, "dedup_hits": 0,
                "mttr_s": 0.0},
               {"key": "kill_router", "requests": 8, "ok": 8,
                "shed": 0, "duplicates": 0, "dedup_hits": 2,
                "mttr_s": 0.5, "adopted": 2, "reaped": 0,
                "adopt_wall_s": 0.4},
               {"key": "kill_replica", "requests": 8, "ok": 8,
                "shed": 1, "duplicates": 0, "dedup_hits": 0,
                "mttr_s": 0.0},
           ]}
    md = recovery_markdown(art)
    lines = [ln for ln in md.splitlines() if ln.startswith("| kill")
             or ln.startswith("| drain")]
    assert lines[0].startswith("| kill_router")
    assert lines[-1].startswith("| drain")
    assert "crash-consistent" in md


# ------------------------------------------------------- chaos e2e


def _spawn_router(jpath, port_file, env):
    if os.path.exists(port_file):
        os.unlink(port_file)
    proc = subprocess.Popen(
        [sys.executable, "-m", "tpu_reductions.serve.router",
         "--replicas", "2", "--platform", "cpu",
         "--journal", jpath, "--port-file", port_file,
         "--max-seconds", "300"],
        env=env, stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL)
    deadline = time.monotonic() + 120
    while time.monotonic() < deadline:
        if proc.poll() is not None:
            raise RuntimeError(f"router died during spawn "
                               f"(exit {proc.returncode})")
        if os.path.exists(port_file):
            return proc
        time.sleep(0.05)
    proc.kill()
    raise TimeoutError("router never published its port")


def _pid_dead(pid):
    try:
        os.kill(pid, 0)
        return False
    except (ProcessLookupError, PermissionError):
        return True


def test_router_crash_recovery_e2e(tmp_path):
    """The tentpole's chaos proof end-to-end: a REAL journaled router
    over two process replicas dies by the scripted `router.crash`
    os._exit mid-burst; the clients retry broken requests with their
    original idempotency keys; a restart against the same journal
    RE-ADOPTS both still-live children (same pids — never respawned);
    every request lands exactly one terminal ok; the ledger-joined
    audit counts ZERO duplicate device executions; teardown leaves
    zero orphaned children."""
    jpath = str(tmp_path / "fleet_journal.json")
    port_file = str(tmp_path / "router.port")
    ledger_path = str(tmp_path / "ledger.jsonl")
    plan = _stamp_idem(
        plan_workload(7, count=12, methods=["SUM", "MIN"], dtype="int",
                      n_choices=[4096], rate_rps=200.0), "e2e-")
    base_env = {k: v for k, v in os.environ.items()
                if not k.startswith("TPU_REDUCTIONS_")}
    base_env["TPU_REDUCTIONS_LEDGER"] = ledger_path
    crash_env = dict(base_env)
    crash_env["TPU_REDUCTIONS_FAULTS"] = json.dumps(
        {"router.crash": {"after": 4, "action": "exit", "code": 86}})

    proc = _spawn_router(jpath, port_file, crash_env)
    procs = [proc]
    try:
        rows = []
        client = threading.Thread(
            target=lambda: rows.extend(
                _recovery_client(port_file, plan, clients=3,
                                 retry_window_s=180.0)),
            daemon=True)
        client.start()
        # the 5th routed submit fires the os._exit — no drain, no
        # atexit, children orphaned alive with work in flight
        assert proc.wait(timeout=120) == 86
        pids = [int(e["pid"]) for e in
                json.loads(open(jpath).read())["replicas"].values()
                if e.get("state") == "up"]
        assert len(pids) == 2
        assert all(not _pid_dead(p) for p in pids)   # orphans live on

        proc2 = _spawn_router(jpath, port_file, base_env)
        procs.append(proc2)
        client.join(timeout=180)
        assert not client.is_alive()
        assert len(rows) == len(plan)
        assert all(r["status"] == "ok" for r in rows), \
            [(r["key"], r["status"], r.get("error")) for r in rows
             if r["status"] != "ok"]
        assert any(r["attempts"] > 1 for r in rows)   # retries happened

        # the successor ADOPTED the orphans: same pids, still alive
        pids_after = [int(e["pid"]) for e in
                      json.loads(open(jpath).read())
                      ["replicas"].values() if e.get("state") == "up"]
        assert sorted(pids_after) == sorted(pids)

        ev = _recovery_evidence(ledger_path, "e2e-")
        assert ev["executed_keys"] == len(plan)
        assert ev["duplicates"] == 0
        assert ev["adopted"] == 2 and ev["reaped"] == 0

        proc2.send_signal(signal.SIGINT)
        assert proc2.wait(timeout=60) == 0
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline \
                and not all(_pid_dead(p) for p in pids):
            time.sleep(0.1)
        assert all(_pid_dead(p) for p in pids)   # zero orphans
    finally:
        for pr in procs:
            if pr.poll() is None:
                pr.send_signal(signal.SIGINT)
        for pr in procs:
            if pr.poll() is None:
                try:
                    pr.wait(timeout=30)
                except subprocess.TimeoutExpired:
                    pr.kill()
        # best-effort INT-first sweep of any child the journal still
        # records (a mid-test failure must not leak serve processes
        # into the rest of the suite)
        try:
            entries = json.loads(open(jpath).read())["replicas"]
        except (OSError, ValueError, KeyError):
            entries = {}
        for e in entries.values():
            pid = e.get("pid")
            if pid and not _pid_dead(int(pid)):
                try:
                    os.kill(int(pid), signal.SIGINT)
                except (ProcessLookupError, PermissionError):
                    pass
