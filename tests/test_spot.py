"""Fixed-geometry spot checks (bench/spot.py): the DOUBLE-scoreboard /
op-parity instrument — several methods at one geometry, one atomic JSON
artifact, rows persisted as they land (the live-window discipline)."""

import json

from tpu_reductions.bench.spot import main, run_spots
from tpu_reductions.config import ReduceConfig

# stable_chained_timing (tests/conftest.py): CLI-shape tests that assert
# PASSED use it so a loaded host's noise-swamped slope cannot flake them


def _base(**kw):
    kw.setdefault("method", "SUM")
    kw.setdefault("dtype", "int32")
    kw.setdefault("n", 1 << 12)
    kw.setdefault("iterations", 8)
    kw.setdefault("timing", "chained")
    kw.setdefault("chain_reps", 2)
    kw.setdefault("log_file", None)
    return ReduceConfig(**kw)


def test_run_spots_covers_all_methods_and_persists_incrementally():
    seen = []
    rows = run_spots(_base(), ["SUM", "MIN", "MAX"],
                     on_result=lambda r: seen.append(r["method"]))
    assert [r["method"] for r in rows] == ["SUM", "MIN", "MAX"]
    assert seen == ["SUM", "MIN", "MAX"]  # fired per row, in order
    assert all(r["status"] in ("PASSED", "WAIVED") for r in rows)
    assert all(r["threads"] == 256 and r["chain_reps"] == 2 for r in rows)


def test_run_spots_contains_a_crashing_method(monkeypatch):
    """One method whose kernel raises must record FAILED and leave the
    other methods' rows intact — a live DOUBLE scoreboard cannot afford
    a process-killing MIN."""
    from tpu_reductions.bench import driver as drv

    real = drv.run_benchmark

    def sabotaged(cfg, **kw):
        if cfg.method == "MIN":
            raise RuntimeError("synthetic dd lowering failure")
        return real(cfg, **kw)

    monkeypatch.setattr(drv, "run_benchmark", sabotaged)
    rows = run_spots(_base(), ["SUM", "MIN", "MAX"])
    by = {r["method"]: r for r in rows}
    assert by["MIN"]["status"] == "FAILED"
    assert by["SUM"]["status"] in ("PASSED", "WAIVED")
    assert by["MAX"]["status"] in ("PASSED", "WAIVED")


def test_spot_cli_double_writes_artifact(tmp_path, capsys,
                                         stable_chained_timing):
    """The chip session's 'double scoreboard' invocation shape, scaled
    down: f64 rows via the dd path, all oracle-verified, artifact
    complete=true."""
    out = tmp_path / "double_spot.json"
    rc = main(["--type=double", "--methods=SUM,MIN,MAX", "--n=16384",
               "--iterations=8", "--chainreps=2", f"--out={out}"])
    assert rc == 0
    data = json.loads(out.read_text())
    assert data["complete"] is True
    assert data["dtype"] == "float64"
    assert [r["method"] for r in data["rows"]] == ["SUM", "MIN", "MAX"]
    assert all(r["status"] == "PASSED" for r in data["rows"])
    assert "wrote" in capsys.readouterr().out


def test_spot_cli_validates_methods():
    import pytest
    with pytest.raises(SystemExit):
        main(["--methods=SUM,NOPE", "--n=64"])


def test_spot_cli_xla_backend(tmp_path, stable_chained_timing):
    """--backend=xla: the comparator at the same spot discipline (the
    'is the MIN deficit ours or the VPU's' instrument)."""
    out = tmp_path / "x.json"
    rc = main(["--type=int", "--methods=SUM,MIN", "--n=16384",
               "--iterations=8", "--chainreps=2", "--backend=xla",
               f"--out={out}"])
    assert rc == 0
    data = json.loads(out.read_text())
    assert all(r["backend"] == "xla" for r in data["rows"])
    assert all(r["status"] == "PASSED" for r in data["rows"])


def test_spot_cli_waived_rows_exit_zero(monkeypatch, tmp_path):
    """Exit contract mirrors the single-chip shmoo: a by-design waiver
    (e.g. --backend=xla --type=double on TPU) is PASSED-or-WAIVED = 0;
    any FAILED row = 1 (round-3 advisor finding)."""
    from tpu_reductions.bench import spot as spot_mod

    def fake_rows(statuses):
        return [{"method": m, "dtype": "float64", "n": 16384,
                 "kernel": None, "threads": 256, "chain_reps": 2,
                 "gbps": None, "status": s, "backend": "xla"}
                for m, s in zip(["SUM", "MIN", "MAX"], statuses)]

    def patched(base, methods, logger=None, on_result=None, resume=None):
        rows = fake_rows(patched.statuses)
        if on_result:
            for r in rows:
                on_result(r)
        return rows

    monkeypatch.setattr(spot_mod, "run_spots", patched)
    patched.statuses = ["WAIVED", "WAIVED", "WAIVED"]
    assert spot_mod.main(["--type=double", "--methods=SUM,MIN,MAX",
                          "--n=16384"]) == 0
    patched.statuses = ["PASSED", "WAIVED", "FAILED"]
    assert spot_mod.main(["--type=double", "--methods=SUM,MIN,MAX",
                          "--n=16384"]) == 1
