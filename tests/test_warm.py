"""bench.warm CLI rehearsal — the ISSUE-8 acceptance pin: a first pass
produces a resumable compile_ledger.json of cold observations, a
second invocation of the same instrumented entry point records warm
verdicts with measurably smaller compile halves, and an interrupted
pass resumes its banked surfaces."""

import json

import pytest

from tpu_reductions.bench import warm
from tpu_reductions.obs import compile as obs_compile
from tpu_reductions.obs import ledger
from tpu_reductions.utils import compile_cache

# a fast, representative slice of the registry: one Pallas kernel, the
# XLA chain, the stream fold, the serve bucket
FAST = "k6,xla,stream,serve-bucket/sum"


@pytest.fixture(autouse=True)
def _isolated(tmp_path, monkeypatch):
    """Each test runs in its own cwd with its own persistent cache —
    the repo-level .jax_cache must not leak warmth into the cold
    assertions."""
    monkeypatch.chdir(tmp_path)
    monkeypatch.delenv("TPU_REDUCTIONS_LEDGER", raising=False)
    monkeypatch.delenv("TPU_REDUCTIONS_COMPILE_LEDGER", raising=False)
    monkeypatch.setattr(compile_cache, "default_dir",
                        lambda: str(tmp_path / "jc"))
    monkeypatch.setattr(compile_cache, "_active_dir", None)
    ledger.disarm()
    obs_compile.disarm()
    yield
    ledger.disarm()
    obs_compile.disarm()


def test_warm_cold_then_warm_acceptance(tmp_path, monkeypatch):
    monkeypatch.setenv("TPU_REDUCTIONS_LEDGER",
                       str(tmp_path / "obs_ledger.jsonl"))
    assert warm.main(["--platform=cpu", f"--only={FAST}",
                      "--out=compile_ledger.json"]) == 0
    data = json.loads((tmp_path / "compile_ledger.json").read_text())
    assert data["complete"] is True
    cold = {r["surface"]: r for r in data["surfaces"]
            if r["verdict"] == "cold"}
    assert set(cold) == set(FAST.split(","))

    # second invocation: same entry point, fresh probes — every
    # surface must come back WARM with a smaller compile half
    obs_compile.disarm()
    assert warm.main(["--platform=cpu", f"--only={FAST}",
                      "--out=compile_ledger.json"]) == 0
    data = json.loads((tmp_path / "compile_ledger.json").read_text())
    warm_rows = {r["surface"]: r for r in data["surfaces"]
                 if r["verdict"] == "warm"}
    assert set(warm_rows) == set(FAST.split(","))
    for surface, row in warm_rows.items():
        assert row["compile_s"] < cold[surface]["compile_s"], surface

    # the ledger carries the typed record of both passes
    evs = [json.loads(line) for line in
           (tmp_path / "obs_ledger.jsonl").read_text().splitlines()]
    verdicts = [e["verdict"] for e in evs if e["ev"] == "compile.end"]
    assert verdicts.count("cold") == len(cold)
    assert verdicts.count("warm") == len(warm_rows)
    assert sum(1 for e in evs if e["ev"] == "warm.end") == 2


def test_warm_resumes_interrupted_pass(tmp_path):
    """A compile_ledger.json left complete:false (an interrupted pass)
    keeps its banked surfaces: the re-invocation probes only the
    rest — the bench/resume contract, observatory spelling."""
    store = obs_compile.CompileLedger("compile_ledger.json")
    store.record({"surface": "k6", "platform": "cpu",
                  "verdict": "cold", "dur_s": 1.0})
    # left complete: false — exactly what a mid-pass death leaves
    assert warm.main(["--platform=cpu", "--only=k6,xla",
                      "--out=compile_ledger.json"]) == 0
    data = json.loads((tmp_path / "compile_ledger.json").read_text())
    surfaces = {(r["surface"], r["verdict"]) for r in data["surfaces"]}
    # k6's banked cold row survived untouched; xla was probed fresh
    assert ("k6", "cold") in surfaces
    k6 = next(r for r in data["surfaces"]
              if r["surface"] == "k6" and r["verdict"] == "cold")
    assert k6["dur_s"] == 1.0          # not re-measured
    assert any(s == "xla" for s, _ in surfaces)
    assert data["complete"] is True


def test_warm_reports_failed_surface_and_continues(tmp_path,
                                                   monkeypatch):
    """A surface that cannot lower is reported, not fatal (the report
    IS the product, like smoke's manifest)."""
    def boom(n):
        raise RuntimeError("no lowering for you")

    monkeypatch.setattr(warm, "surfaces",
                        lambda: [("broken", boom), warm._xla_surface()])
    assert warm.main(["--platform=cpu",
                      "--out=compile_ledger.json"]) == 0
    data = json.loads((tmp_path / "compile_ledger.json").read_text())
    assert {r["surface"] for r in data["surfaces"]} == {"xla"}


def test_warm_all_failed_exits_nonzero(tmp_path, monkeypatch):
    def boom(n):
        raise RuntimeError("nope")

    monkeypatch.setattr(warm, "surfaces", lambda: [("broken", boom)])
    assert warm.main(["--platform=cpu",
                      "--out=compile_ledger.json"]) == 1
