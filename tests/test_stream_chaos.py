"""Chaos e2e for the streaming pipeline (ISSUE 7 satellite;
docs/STREAMING.md resume semantics): a scripted relay flap kills a
real `bench.stream` subprocess mid-stream via the real watchdog
(exit 3) with the partial-accumulator checkpoint persisted; the
re-invocation resumes from the last verified chunk (never re-staging
earlier ones) and lands a final result byte-identical to an
uninterrupted control run's."""

import json
import os
import subprocess
import sys
import time
from pathlib import Path

import pytest

from tpu_reductions.faults.relay import FakeRelay

REPO = Path(__file__).resolve().parent.parent
STREAM_ARGS = ["--platform=cpu", "--method=SUM", "--type=int",
               "--n=65536", "--chunk-bytes=16384", "--sync-every=1"]


def _chaos_env(relay, marker, *, faults=None, ledger=None):
    env = {**os.environ,
           "TPU_REDUCTIONS_CHAOS_ARM": "1",
           "TPU_REDUCTIONS_RELAY_MARKER": str(marker),
           "TPU_REDUCTIONS_RELAY_PORTS": str(relay.port),
           "TPU_REDUCTIONS_WATCHDOG_INTERVAL_S": "0.1",
           "TPU_REDUCTIONS_WATCHDOG_GRACE": "2",
           "TPU_REDUCTIONS_HEALTH_FILE": str(Path(marker).parent
                                             / "health.json")}
    env.pop("TPU_REDUCTIONS_FAULTS", None)
    env.pop("TPU_REDUCTIONS_LEDGER", None)
    if faults is not None:
        env["TPU_REDUCTIONS_FAULTS"] = json.dumps(faults)
    if ledger is not None:
        env["TPU_REDUCTIONS_LEDGER"] = str(ledger)
    return env


def _stream(out: Path, env):
    return subprocess.Popen(
        [sys.executable, "-m", "tpu_reductions.bench.stream",
         *STREAM_ARGS, f"--out={out}"],
        env=env, cwd=str(REPO),
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True)


def _wait_for_sync_rows(out: Path, k: int, timeout_s: float = 30.0):
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        try:
            rows = json.loads(out.read_text()).get("rows", [])
            if sum(1 for r in rows if "partial" in r) >= k:
                return rows
        except (OSError, ValueError):
            pass
        time.sleep(0.05)
    pytest.fail(f"timed out waiting for {k} checkpoint row(s) in {out}")


def test_relay_flap_midstream_exit3_then_resume_byte_identical(tmp_path):
    """The acceptance pipeline for the streaming surface: relay dies
    while a chunk fold wedges -> watchdog exit 3 with the last
    verified partial on disk -> re-invocation resumes from it (zero
    re-staged chunks before the checkpoint) -> final result equals an
    uninterrupted control's byte-for-byte."""
    marker = tmp_path / "relay.marker"
    marker.write_text("tunneled\n")
    out = tmp_path / "stream.json"
    led = tmp_path / "ledger.jsonl"
    with FakeRelay() as relay:
        # chunk 3 wedges in its device window while the relay dies
        # underneath it — the round-2 mid-payload death shape
        env = _chaos_env(relay, marker, ledger=led, faults={
            "stream.chunk": {"after": 3, "action": "stall",
                             "seconds": 120}})
        proc = _stream(out, env)
        _wait_for_sync_rows(out, 2)     # >= 2 checkpoints banked
        relay.force("refuse")
        rc = proc.wait(timeout=60)
        stderr = proc.stderr.read()
        assert rc == 3, f"expected watchdog exit 3, got {rc}: {stderr}"
        interrupted = json.loads(out.read_text())
        assert interrupted["complete"] is False
        banked = [r["chunks_done"] for r in interrupted["rows"]
                  if "partial" in r]
        assert banked and banked == sorted(banked)
        last = banked[-1]
        assert last >= 2                # checkpoints survived the death

        # window 2: relay back, no faults — resume from the checkpoint
        relay.force("accept")
        time.sleep(0.15)
        proc2 = _stream(out, _chaos_env(relay, marker, ledger=led))
        rc2 = proc2.wait(timeout=60)
        stderr2 = proc2.stderr.read()
        assert rc2 == 0, stderr2
        assert "resumed from checkpoint at chunk" in stderr2
        resumed = json.loads(out.read_text())
        assert resumed["complete"] is True
        final = next(r for r in resumed["rows"] if r.get("final"))
        assert final["resumed_from"] == last
        assert final["status"] == "PASSED"

        # uninterrupted control: byte-identical final value
        out2 = tmp_path / "control.json"
        proc3 = _stream(out2, _chaos_env(relay, marker))
        assert proc3.wait(timeout=60) == 0, proc3.stderr.read()
        control = json.loads(out2.read_text())
    cfinal = next(r for r in control["rows"] if r.get("final"))
    assert final["result"] == cfinal["result"]
    assert final["oracle"] == cfinal["oracle"]
    assert resumed["complete"] == control["complete"] is True

    # flight-recorder narrative: the resumed stream declares its
    # start_chunk, and the death window's last act is the banked sync
    from tpu_reductions.obs.timeline import read_ledger, summarize
    events, torn = read_ledger(led)
    assert torn == 0
    starts = [e["start_chunk"] for e in events
              if e["ev"] == "stream.start"]
    assert starts[0] == 0 and last in starts
    summary = summarize(led, events, torn)
    assert summary["stream"]["resumed"] >= 1


def test_stall_midstream_heartbeat_exit4_checkpoints_survive(tmp_path):
    """The stalled-relay variant (ports answer, nothing serviced): the
    stream's heartbeat guard draws exit 4 — not a forever-hang — and
    the checkpoints persisted before the stall resume cleanly."""
    marker = tmp_path / "relay.marker"
    marker.write_text("tunneled\n")
    out = tmp_path / "stream.json"
    with FakeRelay() as relay:
        env = _chaos_env(relay, marker, faults={
            "stream.chunk": {"after": 3, "action": "stall",
                             "seconds": 120}})
        env["TPU_REDUCTIONS_HEARTBEAT_DEADLINE_S"] = "5.0"
        env["TPU_REDUCTIONS_HEARTBEAT_COMPILE_DEADLINE_S"] = "60"
        proc = _stream(out, env)
        _wait_for_sync_rows(out, 2)
        relay.force("stall")            # wedged-but-ports-open
        rc = proc.wait(timeout=60)
        stderr = proc.stderr.read()
        assert rc == 4, f"expected heartbeat exit 4, got {rc}: {stderr}"
        assert "HANG" in stderr
        interrupted = json.loads(out.read_text())
        assert interrupted["complete"] is False

        relay.force("accept")
        time.sleep(0.15)
        proc2 = _stream(out, _chaos_env(relay, marker))
        assert proc2.wait(timeout=60) == 0, proc2.stderr.read()
    resumed = json.loads(out.read_text())
    assert resumed["complete"] is True
    final = next(r for r in resumed["rows"] if r.get("final"))
    assert final["status"] == "PASSED" and final["resumed_from"] >= 2
