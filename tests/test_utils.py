"""L1 tests: QA protocol, timing, logging rows, RNG determinism."""

import io
import time

import numpy as np

from tpu_reductions.utils.logging import (BenchLogger, COLLECTIVE_HEADER,
                                          collective_row, throughput_line)
from tpu_reductions.utils.qa import QAStatus, qa_finish, qa_start
from tpu_reductions.utils.rng import host_data
from tpu_reductions.utils.timing import Stopwatch, TimerRegistry, time_fn


def test_qa_markers():
    # exact shrQATest marker grammar (shrQATest.h:83-112,224-229)
    buf = io.StringIO()
    qa_start("reduction_tpu", ["--method=SUM"], out=buf)
    code = qa_finish("reduction_tpu", QAStatus.PASSED, out=buf)
    lines = buf.getvalue().splitlines()
    assert lines[0] == "&&&& RUNNING reduction_tpu --method=SUM"
    assert lines[1] == "&&&& reduction_tpu PASSED"
    assert code == 0
    assert int(QAStatus.FAILED) == 1 and int(QAStatus.WAIVED) == 2


def test_stopwatch_average():
    sw = Stopwatch()
    for _ in range(3):
        sw.start()
        time.sleep(0.001)
        sw.stop()
    assert sw.sessions == 3
    assert 0.0005 < sw.average_s < 0.1
    sw.reset()
    assert sw.sessions == 0 and sw.total_s == 0.0


def test_timer_registry():
    reg = TimerRegistry()
    reg.create("t")
    reg["t"].start()
    reg["t"].stop()
    assert reg["t"].sessions == 1
    reg.delete("t")


def test_time_fn_counts_iterations():
    import jax.numpy as jnp
    result, sw = time_fn(lambda x: x + 1, jnp.zeros(8), iterations=5, warmup=2)
    assert sw.sessions == 5
    assert float(result[0]) == 1.0


def test_time_fn_modes_agree_on_result():
    import jax.numpy as jnp
    import pytest
    for mode in ("periter", "bulk", "fetch"):
        result, sw = time_fn(lambda x: x * 3, jnp.ones(8), iterations=4,
                             warmup=1, mode=mode)
        assert float(result[0]) == 3.0, mode
        assert sw.sessions == 4 and sw.average_s > 0, mode
    with pytest.raises(ValueError):
        time_fn(lambda x: x, jnp.ones(8), mode="batch")


def test_time_fn_bulk_preserves_accumulated_sessions():
    # regression: bulk mode must not wipe a caller-provided stopwatch
    import jax.numpy as jnp
    sw = Stopwatch()
    time_fn(lambda x: x + 1, jnp.ones(8), iterations=3, warmup=1,
            stopwatch=sw)
    assert sw.sessions == 3
    time_fn(lambda x: x + 1, jnp.ones(8), iterations=5, warmup=0,
            stopwatch=sw, mode="bulk")
    assert sw.sessions == 8 and sw.total_s > 0


def test_reduce_config_validates_timing():
    import pytest
    from tpu_reductions.config import ReduceConfig
    with pytest.raises(ValueError):
        ReduceConfig(method="SUM", timing="Bulk")


def test_throughput_line_format():
    # reduction.cpp:744-745 format
    line = throughput_line(90.8413, 0.00074, 1 << 24, workgroup=256)
    assert line == ("Reduction, Throughput = 90.8413 GB/s, Time = 0.00074 s, "
                    "Size = 16777216 Elements, NumDevsUsed = 1, "
                    "Workgroup = 256")


def test_collective_row_format():
    # reduce.c:81,95 rank-0 schema; getAvgs.sh greps on these fields
    assert collective_row("int32", "SUM", 64, 9.182) == "INT SUM 64 9.182"
    assert collective_row("float64", "MAX", 1024, 90.315) == \
        "DOUBLE MAX 1024 90.315"
    assert COLLECTIVE_HEADER == "DATATYPE OP NODES GB/sec"


def test_logger_fanout(tmp_path):
    app, master = tmp_path / "app.txt", tmp_path / "master.txt"
    console = io.StringIO()
    lg = BenchLogger(str(app), str(master), console=console)
    lg.log("plain")
    lg.log_master("canonical")
    assert "plain" in console.getvalue()
    assert app.read_text() == "plain\ncanonical\n"
    assert master.read_text() == "canonical\n"  # only LOGBOTH|MASTER lines


def test_host_data_deterministic_and_masked():
    a = host_data(1000, "int32", rank=3, seed=7)
    b = host_data(1000, "int32", rank=3, seed=7)
    c = host_data(1000, "int32", rank=4, seed=7)
    np.testing.assert_array_equal(a, b)
    assert not np.array_equal(a, c)  # rank-offset seeding (reduce.c:38-41)
    # masked-byte distribution (reduction.cpp:700): ints in [0, 255]
    assert a.min() >= 0 and a.max() <= 255 and a.dtype == np.int32


def test_host_data_real_distribution():
    x = host_data(1000, "float64", rank=0)
    # (byte)/RAND_MAX: tiny positive reals (reduction.cpp:702-704)
    assert x.dtype == np.float64
    assert (x >= 0).all() and x.max() <= 255 / (2**31 - 1)


def test_bulk_mode_median_falls_back_to_per_iteration_average():
    """Bulk mode books one span; it must NOT surface as a median 'sample'
    (that would inflate per-iteration time by the iteration count)."""
    import jax.numpy as jnp

    from tpu_reductions.utils.timing import time_fn

    f = lambda x: x + 1
    _, sw = time_fn(f, jnp.ones(8), iterations=10, warmup=1, mode="bulk")
    assert sw.sessions == 10
    assert not sw.samples
    assert abs(sw.median_s - sw.average_s) < 1e-12


def test_calibrate_cli_runs_with_default_argv(tmp_path):
    """Bare `python -m tpu_reductions.utils.calibrate` regression pin:
    the argv=None path reads sys.argv (the ledger arm's argv record) —
    in-process tests always pass argv explicitly, which masked a
    NameError that would have crashed the live ladder step (found by
    the scheduler's cpu rehearsal, ISSUE 5)."""
    import subprocess
    import sys as _sys
    from pathlib import Path

    r = subprocess.run(
        [_sys.executable, "-m", "tpu_reductions.utils.calibrate",
         "--platform=cpu", "--n", "16384", "--iters", "2", "--reps",
         "1", "--chainspan", "4"],
        capture_output=True, text=True, timeout=120,
        cwd=str(Path(__file__).parents[1]))
    assert r.returncode == 0, r.stderr


def test_calibrate_ladder_cli_json_shape(capsys):
    """--ladder: two rungs, the HBM-bound (last) rung decides the
    verdict (docs/TIMING.md: VMEM-resident verdicts are vacuous on
    broken-sync tunnels)."""
    import json

    from tpu_reductions.utils.calibrate import main as cal_main

    rc = cal_main(["--n", "65536", "--iters", "4", "--reps", "2",
                   "--chainspan", "8"])
    assert rc == 0
    capsys.readouterr()     # single-size mode works; now the ladder
    rc = cal_main(["--n", "65536", "--iters", "4", "--reps", "2",
                   "--chainspan", "8", "--ladder"])
    assert rc == 0
    out = capsys.readouterr().out.strip().splitlines()[-1]
    d = json.loads(out)
    assert len(d["rungs"]) == 2
    assert d["deciding_n"] == d["rungs"][-1]["n"] == 65536 * 4
    assert d["block_awaits_execution"] == \
        d["rungs"][-1]["block_awaits_execution"]


def test_atomic_json_dump_replaces_never_truncates(tmp_path):
    """utils/jsonio: readers see the old artifact or the new one, never
    a truncation — the contract every mid-run persister relies on."""
    import json

    from tpu_reductions.utils.jsonio import atomic_json_dump

    p = tmp_path / "a.json"
    atomic_json_dump(p, {"v": 1})
    assert json.loads(p.read_text()) == {"v": 1}
    atomic_json_dump(p, {"v": 2, "rows": [1, 2, 3]})
    assert json.loads(p.read_text())["v"] == 2
    assert not (tmp_path / "a.json.tmp").exists()  # temp cleaned up


def test_calibrate_ladder_persists_per_rung(tmp_path, monkeypatch,
                                            capsys):
    """--out persists after EVERY rung (flapping-relay discipline): a
    ladder that dies before the deciding HBM rung leaves a complete:
    false file carrying the VMEM rung and NO verdict fields — a
    partial file must never be mistaken for a decided one."""
    import json

    from tpu_reductions.utils import calibrate as cal_mod

    out = tmp_path / "cal.json"
    rc = cal_mod.main(["--n", "65536", "--iters", "4", "--reps", "2",
                       "--chainspan", "8", "--ladder",
                       "--out", str(out)])
    assert rc == 0
    d = json.loads(out.read_text())
    assert d["complete"] is True and len(d["rungs"]) == 2
    assert d["deciding_n"] == 65536 * 4
    capsys.readouterr()

    # kill the ladder after the first rung: the persisted file must be
    # the partial one
    real = cal_mod.calibrate
    calls = []

    def dies_on_second(**kw):
        if calls:
            raise RuntimeError("synthetic relay death")
        calls.append(kw)
        return real(**kw)

    monkeypatch.setattr(cal_mod, "calibrate", dies_on_second)
    out2 = tmp_path / "cal2.json"
    try:
        cal_mod.main(["--n", "65536", "--iters", "4", "--reps", "2",
                      "--chainspan", "8", "--ladder",
                      "--out", str(out2)])
    except RuntimeError:
        pass
    d2 = json.loads(out2.read_text())
    assert d2["complete"] is False and len(d2["rungs"]) == 1
    assert "deciding_n" not in d2 and "block_awaits_execution" not in d2
