"""Step-0 firstrow (bench/firstrow.py): the minimal relay-window path —
one init, one candidate, persisted + timeline-stamped the moment it
verifies (round-4 verdict do-this #3)."""

import importlib
import json
import os

import tpu_reductions.bench.firstrow as firstrow_mod


def _run(tmp_path, extra=(), reload_env=None, monkeypatch=None):
    out = tmp_path / "FIRSTROW.json"
    if reload_env is not None:
        for k, v in reload_env.items():
            monkeypatch.setenv(k, v)
        importlib.reload(firstrow_mod)
    rc = firstrow_mod.main([
        "--platform=cpu", "--n=65536", "--iterations=8", "--chainreps=2",
        "--doubles-n=16384", "--doubles-reps=2", f"--out={out}",
        *extra])
    return rc, out


def test_firstrow_persists_row_and_timeline(tmp_path):
    rc, out = _run(tmp_path)
    assert rc == 0
    data = json.loads(out.read_text())
    assert data["complete"] is True
    assert data["row"]["status"] == "PASSED"
    assert data["row"]["method"] == "SUM" and data["row"]["dtype"] == "int32"
    labels = [m["label"] for m in data["timeline"]]
    # the timeline IS the rehearsed budget artifact: every stage present,
    # in value order (int row persists BEFORE the doubles are attempted)
    assert any("jax ready" in l for l in labels)
    assert any("int row persisted" in l for l in labels)
    assert any("f64 scoreboard" in l for l in labels)
    assert labels.index(next(l for l in labels if "int row persisted" in l)) \
        < labels.index(next(l for l in labels if "f64 scoreboard" in l))
    assert all(m["t_rel_s"] >= 0 for m in data["timeline"])


def test_firstrow_rehearsal_doubles_avoid_live_contract_path(tmp_path):
    """A cpu rehearsal must write its f64 rows next to --out, never to
    the repo-root BENCH_doubles.json the session exit trap seeds into
    the committed flagship report."""
    repo_doubles = os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "BENCH_doubles.json")
    existed_before = os.path.exists(repo_doubles)
    rc, out = _run(tmp_path)
    assert rc == 0
    side = json.loads((tmp_path / "FIRSTROW.json.doubles.json").read_text())
    assert [r["method"] for r in side["rows"]] == ["SUM", "MIN", "MAX"]
    assert os.path.exists(repo_doubles) == existed_before


def test_firstrow_complete_mark_lands_inside_the_artifact(tmp_path):
    """The 'firstrow complete' mark must be appended BEFORE the final
    persist(complete=True) so total step-0 wall-clock is part of the
    committed FIRSTROW.json (round-5 satellite): the artifact's own
    timeline, not just stderr, answers 'how long did step 0 take'."""
    rc, out = _run(tmp_path)
    assert rc == 0
    data = json.loads(out.read_text())
    assert data["complete"] is True
    assert data["timeline"][-1]["label"] == "firstrow complete"


def test_firstrow_doubles_iterations_not_taken_from_int_row(tmp_path,
                                                            monkeypatch):
    """A rehearsal --iterations override on the int row must NOT leak
    into the doubles scoreboard: leaked, it writes a FLAGSHIP_GRID-
    incompatible yet step-1-suppressing BENCH_doubles.json. Unset, the
    doubles run at the flagship contract; --doubles-iterations is the
    explicit rehearsal knob."""
    seen = {}
    import bench as bench_mod

    real = bench_mod._maybe_double_spots

    def spy(n=None, iterations=None, reps=None, path=None):
        seen["iterations"] = iterations
        return real(n=n, iterations=iterations, reps=reps, path=path)

    monkeypatch.setattr(bench_mod, "_maybe_double_spots", spy)
    rc, _ = _run(tmp_path)   # int row runs --iterations=8
    assert rc == 0
    assert seen["iterations"] is None   # flagship default, not 8

    seen.clear()
    rc, _ = _run(tmp_path, extra=("--doubles-iterations=16",))
    assert rc == 0
    assert seen["iterations"] == 16


def test_firstrow_no_snapshot_off_chip(tmp_path):
    """The flagship-geometry gate: a cpu rehearsal (or a smoke --n) must
    never write the round-headline snapshot."""
    repo_snap = os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "BENCH_snapshot.json")
    before = (open(repo_snap).read() if os.path.exists(repo_snap) else None)
    _run(tmp_path)
    after = (open(repo_snap).read() if os.path.exists(repo_snap) else None)
    assert before == after


def test_firstrow_contains_crash_and_persists_failed_row(tmp_path, monkeypatch):
    """A lowering crash on the first candidate must still leave a FAILED
    row + timeline on disk (the window's post-mortem evidence), exit 1."""
    import tpu_reductions.bench.driver as drv

    def boom(cfg, logger=None, **kw):
        raise RuntimeError("synthetic Mosaic lowering failure")

    monkeypatch.setattr(drv, "run_benchmark", boom)
    # firstrow imports run_benchmark by name; patch its reference too
    rc, out = _run(tmp_path, extra=["--skip-doubles"])
    assert rc == 1
    data = json.loads(out.read_text())
    assert data["row"]["status"] == "FAILED"
    assert data["complete"] is True
    assert any("int row done" in m["label"] for m in data["timeline"])


def test_firstrow_honors_session_t0(tmp_path, monkeypatch):
    """FIRSTROW_T0 (exported by chip_session.sh at session start) is the
    timeline origin: time already burned before python started — bash
    gating, process spawn — must show up in the marks."""
    import time
    monkeypatch.setenv("FIRSTROW_T0", str(time.time() - 100.0))
    importlib.reload(firstrow_mod)
    try:
        rc = firstrow_mod.main([
            "--platform=cpu", "--n=65536", "--iterations=8",
            "--chainreps=2", "--skip-doubles",
            f"--out={tmp_path / 'fr.json'}"])
        assert rc == 0
        data = json.loads((tmp_path / "fr.json").read_text())
        assert data["timeline"][0]["t_rel_s"] >= 100.0
    finally:
        monkeypatch.delenv("FIRSTROW_T0")
        importlib.reload(firstrow_mod)
