"""Watcher supervisor rehearsal (scripts/supervise_watcher.sh).

Round-4 postmortem: the relay's only flap opened while the watcher
process was dead, and ~4 of ~6 live minutes were lost before a human
spotted it; the watcher's 12 h horizon also expired unattended. The
supervisor makes "armed" a process-level invariant. These tests drive
it against a fake await_window in a temp git repo (SUP_ROOT) and prove
the contracts the round-5 verdict asked for:

  * a killed watcher is respawned well within one poll interval;
  * a dead watcher's surviving subtree (the chip-session pipeline) is
    REAPED before a successor is armed — two concurrent sessions on one
    relay window is the documented machine-wide chip-wedge hazard;
  * a horizon expiry (rc=4) re-arms with a fresh horizon;
  * a COMPLETED session (rc=0) retires the supervisor, subtree and all;
  * a second supervisor refuses to double-arm (flock guard).

The fakes `exec` into their long-lived process so the recorded pid IS
the thing that must die — a fake that merely spawns `sleep` would leak
orphans and mask exactly the subtree-escape bug the supervisor fixes.
"""

import os
import signal
import subprocess
import time
from pathlib import Path

import pytest

REPO = Path(__file__).resolve().parent.parent
SUPERVISOR = REPO / "scripts" / "supervise_watcher.sh"


def _git_init(root: Path) -> None:
    subprocess.run(["git", "init", "-q"], cwd=root, check=True)
    subprocess.run(["git", "config", "user.email", "t@t"], cwd=root,
                   check=True)
    subprocess.run(["git", "config", "user.name", "t"], cwd=root,
                   check=True)


def _write_fake_await(root: Path, body: str) -> Path:
    """A fake await_window.sh; records each invocation's pid so the
    tests can observe spawns and kill specific generations."""
    fake = root / "fake_await.sh"
    fake.write_text("#!/usr/bin/env bash\n"
                    "echo $$ >> spawn_pids.txt\n" + body + "\n")
    fake.chmod(0o755)
    return fake


def _spawn_supervisor(root: Path, fake: Path, **env_over):
    env = {**os.environ,
           "SUP_ROOT": str(root),
           "AWAIT_BIN": str(fake),
           "WATCH_LOG": "watch.log",
           "CHECK_S": "1",
           "RESPAWN_DELAY_S": "0",
           "COMMIT_EVERY_S": "0",
           "GRACE_S": "3",
           # any file that exists: the untunneled-host early exit must
           # not fire on rehearsal hosts without the real relay marker
           "RELAY_MARKER": str(fake),
           "FLOCK_WAIT_S": "1",
           **env_over}
    return subprocess.Popen(["bash", str(SUPERVISOR)], cwd=root, env=env,
                            stdout=subprocess.DEVNULL,
                            stderr=subprocess.DEVNULL)


def _pids(root: Path):
    f = root / "spawn_pids.txt"
    if not f.exists():
        return []
    return [int(x) for x in f.read_text().split()]


def _alive(pid: int) -> bool:
    try:
        os.kill(pid, 0)
        return True
    except OSError:
        return False


def _wait_for(cond, timeout_s: float, what: str):
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        if cond():
            return
        time.sleep(0.1)
    pytest.fail(f"timed out after {timeout_s}s waiting for {what}")


def _stop(proc):
    if proc.poll() is None:
        proc.send_signal(signal.SIGTERM)
    try:
        proc.wait(timeout=15)
    except subprocess.TimeoutExpired:
        proc.kill()
        proc.wait(timeout=10)


def test_killed_watcher_respawns_within_poll_interval(tmp_path):
    """THE round-4 failure mode: the watcher process dies while the
    relay is dead; a window that opens next must still find one armed.
    Done-criterion: restart within one poll interval (20 s)."""
    _git_init(tmp_path)
    fake = _write_fake_await(tmp_path, "exec sleep 600")
    sup = _spawn_supervisor(tmp_path, fake)
    try:
        _wait_for(lambda: len(_pids(tmp_path)) >= 1, 15, "first arm")
        first = _pids(tmp_path)[0]
        killed_at = time.monotonic()
        os.kill(first, signal.SIGKILL)
        _wait_for(lambda: len(_pids(tmp_path)) >= 2, 15, "respawn")
        elapsed = time.monotonic() - killed_at
        assert elapsed < 20, f"respawn took {elapsed:.1f}s (> poll interval)"
        second = _pids(tmp_path)[1]
        assert second != first
        assert _alive(second)  # the respawned generation is genuinely alive
        log = (tmp_path / "watch.log").read_text()
        assert "watcher DIED" in log
        assert log.count("watcher armed") >= 2
    finally:
        _stop(sup)


def test_respawn_reaps_dead_watchers_surviving_subtree(tmp_path):
    """A watcher bash that dies mid-chip-session leaves the session
    subtree alive (a bash's foreground child outlives the killed bash);
    arming a successor NEXT TO a live orphaned session would let two
    sessions share the relay — the wedge hazard. The supervisor must
    group-reap survivors before respawning."""
    _git_init(tmp_path)
    fake = _write_fake_await(
        tmp_path,
        # grandchild = the surviving "session subtree"
        "sleep 600 & echo $! >> grandchild.txt\nexec sleep 600")
    sup = _spawn_supervisor(tmp_path, fake)
    try:
        _wait_for(lambda: (tmp_path / "grandchild.txt").exists(), 15,
                  "first arm + grandchild")
        first = _pids(tmp_path)[0]
        gchild = int((tmp_path / "grandchild.txt").read_text().split()[0])
        assert _alive(gchild)
        os.kill(first, signal.SIGKILL)   # watcher dies; grandchild survives
        _wait_for(lambda: len(_pids(tmp_path)) >= 2, 20, "respawn")
        _wait_for(lambda: not _alive(gchild), 10,
                  "orphaned subtree reaped before/at respawn")
    finally:
        _stop(sup)


def test_horizon_expiry_rearms_with_fresh_horizon(tmp_path):
    """await_window exits 4 when its horizon lapses; round 4's log ended
    exactly there ('giving up' at 15:41Z) with nothing to re-arm it.
    The supervisor must treat rc=4 as re-arm, not retire."""
    _git_init(tmp_path)
    fake = _write_fake_await(
        tmp_path,
        # first invocation: horizon expiry; later ones: keep polling
        'n=$(wc -l < spawn_pids.txt); [ "$n" -le 1 ] && exit 4; exec sleep 600')
    sup = _spawn_supervisor(tmp_path, fake)
    try:
        _wait_for(lambda: len(_pids(tmp_path)) >= 2, 20, "re-arm after rc=4")
        log = (tmp_path / "watch.log").read_text()
        assert "horizon expired (rc=4); re-arming" in log
        assert sup.poll() is None, "supervisor must not retire on rc=4"
    finally:
        _stop(sup)


def test_completed_session_retires_supervisor(tmp_path):
    """rc=0 = a chip session ran to completion: the one and only event
    that retires the watcher stack (await_window contract, preserved)."""
    _git_init(tmp_path)
    fake = _write_fake_await(tmp_path, "exit 0")
    sup = _spawn_supervisor(tmp_path, fake)
    try:
        _wait_for(lambda: sup.poll() is not None, 20, "supervisor retire")
        assert sup.returncode == 0
        log = (tmp_path / "watch.log").read_text()
        assert "COMPLETED" in log
        # retirement leaves no orphan watcher
        assert all(not _alive(p) for p in _pids(tmp_path))
    finally:
        _stop(sup)


def test_supervisor_teardown_kills_watcher_subtree(tmp_path):
    """Killing the supervisor must not leak an unsupervised watcher OR
    its session subtree — that would silently recreate the round-4
    posture (a process tree nobody supervises) while looking armed."""
    _git_init(tmp_path)
    fake = _write_fake_await(
        tmp_path, "sleep 600 & echo $! >> grandchild.txt\nexec sleep 600")
    sup = _spawn_supervisor(tmp_path, fake)
    try:
        _wait_for(lambda: (tmp_path / "grandchild.txt").exists(), 15,
                  "first arm + grandchild")
        watcher = _pids(tmp_path)[-1]
        gchild = int((tmp_path / "grandchild.txt").read_text().split()[0])
        assert _alive(watcher) and _alive(gchild)
    finally:
        _stop(sup)
    _wait_for(lambda: not _alive(watcher), 10, "watcher reaped on teardown")
    _wait_for(lambda: not _alive(gchild), 10, "subtree reaped on teardown")


def test_crash_looping_watcher_backs_off(tmp_path):
    """A persistently failing AWAIT_BIN (wrong path, syntax error) must
    not be respawned every ~2 s for the whole 20 h horizon — that's
    ~50k garbage log lines auto-committed hourly. Capped exponential
    backoff bounds the churn while staying armed."""
    _git_init(tmp_path)
    fake = _write_fake_await(tmp_path, "exit 1")
    sup = _spawn_supervisor(tmp_path, fake)
    try:
        _wait_for(lambda: "backing off" in
                  ((tmp_path / "watch.log").read_text()
                   if (tmp_path / "watch.log").exists() else ""),
                  15, "backoff note")
        time.sleep(4)
        # without backoff ~4 respawns would land in these 4 s on top of
        # the pre-backoff churn; with the exponential schedule
        # (2,4,8,... s) only a couple can
        assert len(_pids(tmp_path)) <= 5
        assert sup.poll() is None, "must stay armed (backoff, not bail)"
    finally:
        _stop(sup)


def test_sigkilled_supervisor_replacement_reaps_orphan_and_arms(tmp_path):
    """SIGKILL skips the EXIT trap: the watcher survives as an orphan.
    A replacement supervisor must (a) not be refused by an inherited
    lock fd, and (b) REAP the orphan before arming its own watcher —
    two watchers would fire two concurrent sessions at the next flap
    (review findings, both).

    This fake does NOT exec: the predecessor check verifies the
    recorded pid still looks like a watcher via /proc cmdline (pid-reuse
    safety), and a real await_window stays `bash .../await_window.sh`
    for its whole life. A bash-plus-child fake also makes the reap
    cover a subtree, like a real watcher mid-session."""
    _git_init(tmp_path)
    fake = _write_fake_await(tmp_path, "sleep 600 & wait $!")
    sup1 = _spawn_supervisor(tmp_path, fake)
    orphan = None
    try:
        _wait_for(lambda: len(_pids(tmp_path)) >= 1, 15, "first arm")
        orphan = _pids(tmp_path)[0]
        sup1.kill()          # no trap: watcher survives as an orphan
        sup1.wait(timeout=10)
        assert _alive(orphan)
        sup2 = _spawn_supervisor(tmp_path, fake)
        try:
            _wait_for(lambda: len(_pids(tmp_path)) >= 2, 15,
                      "replacement supervisor arms (lock was NOT inherited)")
            assert sup2.poll() is None
            _wait_for(lambda: not _alive(orphan), 10,
                      "orphaned predecessor watcher reaped before arming")
            log = (tmp_path / "watch.log").read_text()
            assert "reaping orphaned predecessor watcher" in log
        finally:
            _stop(sup2)
    finally:
        if orphan is not None and _alive(orphan):
            try:
                os.killpg(orphan, signal.SIGKILL)
            except OSError:
                os.kill(orphan, signal.SIGKILL)


def test_untunneled_host_exits_without_arming(tmp_path):
    """Mirrors await_window's own untunneled-host contract — and guards
    the rc=0 retire path: await_window exits 0 when the relay marker is
    missing, which must never be logged as 'session COMPLETED'."""
    _git_init(tmp_path)
    fake = _write_fake_await(tmp_path, "exec sleep 600")
    sup = _spawn_supervisor(tmp_path, fake,
                            RELAY_MARKER=str(tmp_path / "no-such-marker"))
    try:
        _wait_for(lambda: sup.poll() is not None, 15, "untunneled early exit")
        assert sup.returncode == 0
        assert _pids(tmp_path) == [], "must not arm a watcher untunneled"
    finally:
        _stop(sup)


def test_stubborn_nonsession_straggler_is_killed_not_waited_on(tmp_path):
    """A group member that ignores INT but is NOT session work (no
    device queue to wedge) must be SIGKILLed after the grace — not
    given the no-KILL session drain, which would strand the supervisor
    in the defer loop for a process that can never wedge anything."""
    _git_init(tmp_path)
    fake = _write_fake_await(
        tmp_path,
        # stubborn straggler: ignores INT (disposition survives exec)
        'bash -c \'trap "" INT; echo $$ >> stubborn.txt; exec sleep 600\' &\n'
        "exec sleep 600")
    sup = _spawn_supervisor(tmp_path, fake)
    stubborn = None
    try:
        _wait_for(lambda: (tmp_path / "stubborn.txt").exists(), 15,
                  "first arm + stubborn straggler")
        stubborn = int((tmp_path / "stubborn.txt").read_text().split()[0])
        assert _alive(stubborn)
    finally:
        _stop(sup)
    # INT leaves it alive; the KILL backstop (after GRACE_S=3) must not
    _wait_for(lambda: not _alive(stubborn), 15,
              "stubborn straggler SIGKILLed after grace")


def test_second_supervisor_refuses_to_double_arm(tmp_path):
    """Two supervisors = two watchers = two concurrent chip sessions at
    the same window. The flock guard makes 'armed' singular."""
    _git_init(tmp_path)
    fake = _write_fake_await(tmp_path, "exec sleep 600")
    sup1 = _spawn_supervisor(tmp_path, fake)
    try:
        _wait_for(lambda: len(_pids(tmp_path)) >= 1, 15, "first arm")
        sup2 = _spawn_supervisor(tmp_path, fake)
        _wait_for(lambda: sup2.poll() is not None, 15, "second refuses")
        assert sup2.returncode == 1
        assert len(_pids(tmp_path)) == 1, "second supervisor must not arm"
        assert sup1.poll() is None
    finally:
        _stop(sup1)
