"""Satellite (ISSUE 3): bench/resume.Checkpoint adoption in the
collective driver's --out path and sweep_collective — an interrupted
rank-scaling sweep resumes its per-rank-count rows instead of
restarting the 2..1024 ladder."""

import json
from pathlib import Path

from tpu_reductions.bench.collective_driver import (collective_meta,
                                                    run_collective_benchmark)
from tpu_reductions.bench.resume import Checkpoint
from tpu_reductions.bench.sweep import sweep_collective
from tpu_reductions.config import CollectiveConfig
from tpu_reductions.utils.logging import BenchLogger


def _mark_incomplete(path: Path) -> None:
    data = json.loads(path.read_text())
    data["complete"] = False
    path.write_text(json.dumps(data))


def test_collective_checkpoint_persists_and_resumes(tmp_path):
    out = tmp_path / "coll.json"
    cfg = CollectiveConfig(method="SUM", dtype="int32", n=4096,
                           retries=2, num_devices=4)
    ck = Checkpoint(out, collective_meta(cfg),
                    key_fn=lambda r: r.get("repeat"))
    fresh = run_collective_benchmark(cfg, checkpoint=ck)
    ck.finalize()
    data = json.loads(out.read_text())
    assert data["complete"] is True
    assert [r["repeat"] for r in data["rows"]] == [0, 1]
    assert all(r["status"] == "PASSED" for r in data["rows"])

    # interrupted artifact: re-invocation reuses the rows byte-
    # identically, without re-measuring (reuse logs the resume note)
    _mark_incomplete(out)
    lines = []

    class _Log(BenchLogger):
        def log(self, msg):
            lines.append(msg)

    ck2 = Checkpoint(out, collective_meta(cfg),
                     key_fn=lambda r: r.get("repeat"))
    resumed = run_collective_benchmark(cfg, logger=_Log(None, None),
                                       checkpoint=ck2)
    ck2.finalize()
    assert any("resumed from prior artifact" in ln for ln in lines)
    assert [r.to_dict() for r in resumed] == [r.to_dict() for r in fresh]
    after = json.loads(out.read_text())
    assert after["rows"] == data["rows"]
    assert after["complete"] is True


def test_collective_checkpoint_contract_mismatch_remeasures(tmp_path):
    out = tmp_path / "coll.json"
    cfg = CollectiveConfig(method="SUM", dtype="int32", n=4096,
                           retries=2, num_devices=4)
    ck = Checkpoint(out, collective_meta(cfg),
                    key_fn=lambda r: r.get("repeat"))
    run_collective_benchmark(cfg, checkpoint=ck)
    _mark_incomplete(out)
    # a different geometry is a different measurement: nothing resumes
    other = CollectiveConfig(method="SUM", dtype="int32", n=8192,
                             retries=2, num_devices=4)
    ck2 = Checkpoint(out, collective_meta(other),
                     key_fn=lambda r: r.get("repeat"))
    assert ck2.resume(0) is None


def test_collective_cli_out_writes_checkpoint_artifact(tmp_path, capsys):
    from tpu_reductions.bench import collective_driver

    out = tmp_path / "cli.json"
    rc = collective_driver.main(["--method=SUM", "--type=int",
                                 "--n=4096", "--devices=4",
                                 "--retries=2", f"--out={out}"])
    assert rc == 0
    data = json.loads(out.read_text())
    assert data["complete"] is True
    assert len(data["rows"]) == 2
    assert data["method"] == "SUM" and data["n"] == 4096


def test_sweep_collective_resumes_per_rank_count_rows(tmp_path):
    """The run_rank_scaling.sh contract: an interrupted sweep's
    per-rank-count rows are reused on re-invocation (whole-config
    grain), and the stdout-analog job files still reconstruct
    completely from the reused rows."""
    kwargs = dict(rank_counts=(2, 4), methods=("SUM",),
                  dtypes=("int32",), n=1 << 12, retries=2,
                  out_dir=str(tmp_path))
    first = sweep_collective(**kwargs)
    artifact = tmp_path / "collective_sweep.json"
    data = json.loads(artifact.read_text())
    assert data["complete"] is True
    assert len(data["rows"]) == 4            # 2 ranks x 2 reps

    _mark_incomplete(artifact)
    second = sweep_collective(**kwargs)
    after = json.loads(artifact.read_text())
    assert after["rows"] == data["rows"]     # byte-identical reuse
    assert after["complete"] is True
    assert [(r["ranks"], r["repeat"]) for r in second] \
        == [(r["ranks"], r["repeat"]) for r in first]
    # the per-job stdout-analog files reconstruct (header + rows) even
    # though every row was reused, so aggregate.pipeline still works
    for k in (2, 4):
        txt = (tmp_path / "raw_output"
               / f"stdout-vn-{k}ranks.txt").read_text()
        rows = [ln for ln in txt.splitlines()
                if ln.split()[:1] == ["INT"]]
        assert len(rows) == 2, txt
