"""Chaos layer (tpu_reductions/faults/): the scriptable fake relay,
the env-driven fault points, and the device-call retry classifier —
the machinery that makes every relay-flap failure path testable
off-chip (docs/RESILIENCE.md)."""

import json
import socket
import time

import pytest

from tpu_reductions.faults import inject
from tpu_reductions.faults.inject import InjectedFault, fault_point
from tpu_reductions.faults.relay import FakeRelay
from tpu_reductions.faults.schedule import Phase, load_schedule
from tpu_reductions.utils.retry import retry_device_call
from tpu_reductions.utils.watchdog import probe_relay


# ---------------------------------------------------------------- schedule


def test_schedule_parses_json_and_validates():
    phases = load_schedule('[{"behavior": "accept", "connections": 2},'
                           ' {"behavior": "refuse", "duration_s": 1},'
                           ' {"behavior": "stall"}]')
    assert [p.behavior for p in phases] == ["accept", "refuse", "stall"]
    assert phases[0].connections == 2 and phases[1].duration_s == 1


def test_schedule_rejects_malformed():
    with pytest.raises(ValueError):
        load_schedule("[]")                       # empty tests nothing
    with pytest.raises(ValueError):
        load_schedule('[{"behavior": "explode"}]')
    with pytest.raises(ValueError):
        # refused connects never reach userspace: count-advance invalid
        load_schedule('[{"behavior": "refuse", "connections": 1}]')
    with pytest.raises(ValueError):
        load_schedule('[{"behavior": "accept", "duration_s": 1,'
                      ' "connections": 1}]')
    with pytest.raises(ValueError):
        load_schedule('[{"behavior": "accept", "typo_s": 1}]')


def test_schedule_loads_from_file(tmp_path):
    f = tmp_path / "flap.json"
    f.write_text('[{"behavior": "accept"}]')
    assert load_schedule(str(f))[0].behavior == "accept"


# ---------------------------------------------------------------- FakeRelay


def test_fake_relay_flap_schedule_drives_probe_verdicts():
    """The canonical flap — accept, die, come back — as seen by the
    very probe the watchdog uses."""
    with FakeRelay([Phase("accept", connections=2),
                    Phase("refuse", duration_s=0.4),
                    Phase("accept")]) as relay:
        ports = (relay.port,)
        assert probe_relay(ports=ports) == "alive"
        assert probe_relay(ports=ports) == "alive"   # advances phase
        time.sleep(0.1)
        assert probe_relay(ports=ports, timeout_s=0.3) == "dead"
        time.sleep(0.6)
        assert probe_relay(ports=ports) == "alive"   # relay flapped back
        # the serve loop books the accept a tick after the kernel
        # completes the connect: poll rather than race it
        deadline = time.monotonic() + 2.0
        while relay.connections < 3 and time.monotonic() < deadline:
            time.sleep(0.02)
        assert relay.connections >= 3


def test_fake_relay_force_overrides_schedule():
    """force() is the deterministic flip the e2e tests use: no racing
    wall-clock phases."""
    with FakeRelay() as relay:
        assert probe_relay(ports=(relay.port,)) == "alive"
        relay.force("refuse")
        time.sleep(0.15)   # let the serve loop close the listener
        assert probe_relay(ports=(relay.port,), timeout_s=0.3) == "dead"
        relay.force("accept")
        time.sleep(0.15)
        assert probe_relay(ports=(relay.port,)) == "alive"


def test_fake_relay_stall_is_wedged_but_ports_open():
    """A stalled relay ACCEPTS connections (probes say alive) but never
    services them — the wedged-tunnel case budgets exist for."""
    with FakeRelay([Phase("stall")]) as relay:
        assert probe_relay(ports=(relay.port,)) == "alive"
        with socket.create_connection(("127.0.0.1", relay.port),
                                      timeout=2) as s:
            s.settimeout(0.3)
            with pytest.raises(socket.timeout):
                s.recv(1)   # held open, never answered


def test_fake_relay_slow_injects_per_connection_latency():
    """The `slow` latency-injection mode (ISSUE 6): probes still say
    alive, but a consumer that waits for service (recv to EOF — the
    serving engine's transport gate) pays ~delay_s per round-trip."""
    with FakeRelay([Phase("slow", delay_s=0.3)]) as relay:
        assert probe_relay(ports=(relay.port,)) == "alive"
        t0 = time.monotonic()
        with socket.create_connection(("127.0.0.1", relay.port),
                                      timeout=2) as s:
            s.settimeout(5)
            while s.recv(64):
                pass                       # drains until the late close
        held = time.monotonic() - t0
        assert held >= 0.25, f"slow relay closed after only {held:.3f}s"


def test_fake_relay_force_slow_with_explicit_delay():
    with FakeRelay() as relay:
        relay.force("slow", delay_s=0.2)
        time.sleep(0.15)   # let the serve loop observe the new behavior
        t0 = time.monotonic()
        with socket.create_connection(("127.0.0.1", relay.port),
                                      timeout=2) as s:
            s.settimeout(5)
            while s.recv(64):
                pass
        assert time.monotonic() - t0 >= 0.15


def test_schedule_slow_delay_validation():
    """delay_s is slow-only and must be positive; slow without it gets
    the documented default hold."""
    from tpu_reductions.faults.schedule import (DEFAULT_SLOW_DELAY_S,
                                                load_schedule)
    ph = load_schedule('[{"behavior": "slow", "delay_s": 0.5}]')[0]
    assert ph.hold_s == 0.5
    assert load_schedule('[{"behavior": "slow"}]')[0].hold_s \
        == DEFAULT_SLOW_DELAY_S
    with pytest.raises(ValueError):
        load_schedule('[{"behavior": "accept", "delay_s": 0.5}]')
    with pytest.raises(ValueError):
        load_schedule('[{"behavior": "slow", "delay_s": 0}]')


# ---------------------------------------------------------------- inject


@pytest.fixture(autouse=True)
def _clean_faults(monkeypatch):
    monkeypatch.delenv(inject.ENV_VAR, raising=False)
    inject.reset()
    yield
    inject.reset()


def test_fault_point_noop_without_plan():
    assert fault_point("bench.run") is None


def test_fault_point_after_and_times_window(monkeypatch):
    """`after` skips hits, `times` bounds firing — the flap model: the
    point fails transiently, then 'recovers' and never fires again."""
    monkeypatch.setenv(inject.ENV_VAR, json.dumps(
        {"bench.run": {"after": 1, "times": 2, "action": "raise"}}))
    inject.reset()
    assert fault_point("bench.run") is None          # hit 0: before after
    with pytest.raises(InjectedFault):
        fault_point("bench.run")                     # hit 1
    with pytest.raises(InjectedFault):
        fault_point("bench.run")                     # hit 2
    assert fault_point("bench.run") is None          # recovered
    assert fault_point("other.point") is None        # unplanned point


def test_fault_point_passive_specs_returned(monkeypatch):
    monkeypatch.setenv(inject.ENV_VAR, json.dumps(
        {"watchdog.probe": {"action": "dead"}}))
    inject.reset()
    spec = fault_point("watchdog.probe")
    assert spec is not None and spec["action"] == "dead"


def test_fault_plan_from_file(tmp_path, monkeypatch):
    plan = tmp_path / "plan.json"
    plan.write_text(json.dumps({"staging.chunk": {"action": "raise"}}))
    monkeypatch.setenv(inject.ENV_VAR, f"@{plan}")
    inject.reset()
    with pytest.raises(InjectedFault):
        fault_point("staging.chunk")


def test_fault_plan_malformed_is_loud(monkeypatch):
    """A chaos run whose plan silently parses to nothing would test
    nothing while looking green."""
    monkeypatch.setenv(inject.ENV_VAR, "{not json")
    inject.reset()
    with pytest.raises(ValueError):
        fault_point("bench.run")


# ---------------------------------------------------------------- retry


def test_retry_transient_flap_retries_then_succeeds():
    calls = []

    def flaky():
        calls.append(None)
        if len(calls) < 3:
            raise RuntimeError("tunnel hiccup")
        return "row"

    slept = []
    out = retry_device_call(flaky, retries=3, backoff_s=0.01,
                            _sleep=slept.append,
                            _tunneled=lambda: True,
                            _alive=lambda: True)
    assert out == "row" and len(calls) == 3
    assert slept == [0.01, 0.02]   # bounded exponential backoff


def test_retry_dead_relay_is_fatal_immediately():
    """A dead relay never comes back in-session: retrying can only
    hang — defer to the watchdog (re-raise on the first failure)."""
    calls = []

    def dies():
        calls.append(None)
        raise RuntimeError("relay gone")

    with pytest.raises(RuntimeError):
        retry_device_call(dies, retries=5, backoff_s=0.01,
                          _sleep=lambda s: None,
                          _tunneled=lambda: True,
                          _alive=lambda: False)
    assert len(calls) == 1


def test_retry_untunneled_error_is_deterministic_no_retry():
    calls = []

    def broken():
        calls.append(None)
        raise ValueError("lowering gap")

    with pytest.raises(ValueError):
        retry_device_call(broken, retries=5, backoff_s=0.01,
                          _sleep=lambda s: None,
                          _tunneled=lambda: False,
                          _alive=lambda: True)
    assert len(calls) == 1


def test_retry_budget_exhaustion_reraises_last_error():
    with pytest.raises(RuntimeError, match="still flapping"):
        retry_device_call(
            lambda: (_ for _ in ()).throw(RuntimeError("still flapping")),
            retries=2, backoff_s=0.01, _sleep=lambda s: None,
            _tunneled=lambda: True, _alive=lambda: True)


def test_retry_env_budget(monkeypatch):
    from tpu_reductions.utils.retry import retry_budget
    monkeypatch.setenv("TPU_REDUCTIONS_DEVICE_RETRIES", "0")
    assert retry_budget() == 0
    assert retry_budget(4) == 4   # explicit argument wins
    monkeypatch.delenv("TPU_REDUCTIONS_DEVICE_RETRIES")
    from tpu_reductions.utils.retry import DEFAULT_RETRIES
    assert retry_budget() == DEFAULT_RETRIES


# ------------------------------------------------- injected probe loop


def test_watchdog_probe_fault_fires_exit(monkeypatch):
    """The watchdog probe loop consults the `watchdog.probe` fault
    point: a scripted dead verdict must walk the grace counter to the
    exit exactly like a real outage."""
    import threading

    from tpu_reductions.utils.watchdog import (WATCHDOG_EXIT_CODE,
                                               start_relay_watchdog)

    monkeypatch.setenv(inject.ENV_VAR, json.dumps(
        {"watchdog.probe": {"action": "dead"}}))
    inject.reset()
    fired = threading.Event()
    codes = []

    def fake_exit(code):
        codes.append(code)
        fired.set()

    stop = start_relay_watchdog(interval_s=0.02, grace=2,
                                _probe=lambda: True, _exit=fake_exit)
    try:
        assert stop is not None
        assert fired.wait(timeout=5.0)
        assert codes[0] == WATCHDOG_EXIT_CODE
    finally:
        stop.set()
