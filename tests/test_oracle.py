"""Host-oracle tests: Kahan accuracy, wrapping int sum, native/numpy parity."""

import math

import numpy as np
import pytest

from tpu_reductions.ops import oracle


def test_int32_sum_wraps():
    # int32 accumulator wraps mod 2^32, matching device semantics
    # (reduction.cpp:748,776-777 — int compare is exact-match)
    x = np.array([2**31 - 1, 2**31 - 1, 5], dtype=np.int32)
    got = oracle.host_reduce(x, "SUM")
    expect = np.int64(int(x[0]) + int(x[1]) + 5).astype(np.int32)  # wraps
    assert got == expect


def test_kahan_beats_naive_f32():
    # an adversarial payload where naive f32 summation visibly drifts
    rng = np.random.default_rng(0)
    x = rng.uniform(0, 1, size=1 << 16).astype(np.float32)
    exact = math.fsum(x.astype(np.float64).tolist())
    got = float(oracle.host_reduce(x, "SUM"))
    assert abs(got - exact) < 1e-6


def test_f64_sum_matches_fsum():
    rng = np.random.default_rng(1)
    x = rng.uniform(0, 1e-7, size=1 << 14)
    exact = math.fsum(x.tolist())
    got = float(oracle.host_reduce(x, "SUM"))
    assert abs(got - exact) < 1e-15


@pytest.mark.parametrize("dtype", ["int32", "float32", "float64"])
@pytest.mark.parametrize("method", ["MIN", "MAX"])
def test_minmax(dtype, method):
    rng = np.random.default_rng(2)
    x = (rng.integers(-1000, 1000, 4097).astype(dtype) if dtype == "int32"
         else rng.standard_normal(4097).astype(dtype))
    got = oracle.host_reduce(x, method)
    expect = x.min() if method == "MIN" else x.max()
    assert got == expect and got.dtype == x.dtype


def test_native_and_fallback_agree(monkeypatch):
    rng = np.random.default_rng(3)
    x32 = rng.uniform(0, 1, 10_001).astype(np.float32)
    xi = rng.integers(0, 256, 10_001).astype(np.int32)
    cases = [("SUM", x32), ("MIN", x32), ("MAX", x32), ("SUM", xi)]
    res_native = [oracle.host_reduce(arr, m) for m, arr in cases]
    # force the numpy fallback
    monkeypatch.setattr(oracle, "_lib", None)
    monkeypatch.setattr(oracle, "_lib_tried", True)
    for (m, arr), val in zip(cases, res_native):
        fb = oracle.host_reduce(arr, m)
        assert abs(float(fb) - float(val)) < 1e-9


def test_native_fill_matches_distribution():
    x = oracle.native_fill(1 << 12, "int32", rank=1, seed=0)
    if x is None:
        pytest.skip("native oracle not built")
    assert x.min() >= 0 and x.max() <= 255
    y = oracle.native_fill(1 << 12, "int32", rank=1, seed=0)
    np.testing.assert_array_equal(x, y)  # deterministic per (rank, seed)
    z = oracle.native_fill(1 << 12, "int32", rank=2, seed=0)
    assert not np.array_equal(x, z)


def test_verify_tolerances():
    # acceptance rule parity (reduction.cpp:750,763-765,776-779)
    ok, _ = oracle.verify(100, 100, "SUM", "int32", 1 << 24)
    bad, _ = oracle.verify(100, 101, "SUM", "int32", 1 << 24)
    assert ok and not bad
    n = 1 << 24
    ok, _ = oracle.verify(1.0 + 0.5e-8 * n, 1.0, "SUM", "float32", n)
    bad, _ = oracle.verify(1.0 + 2e-8 * n, 1.0, "SUM", "float32", n)
    assert ok and not bad
    ok, _ = oracle.verify(1.0 + 0.5e-12, 1.0, "SUM", "float64", n)
    bad, _ = oracle.verify(1.0 + 2e-12, 1.0, "SUM", "float64", n)
    assert ok and not bad
