"""Compile observatory coverage (ISSUE 8): the cache-fingerprint
verdict, the span/probe event contract, the persisted per-surface
ledger and its read model, the instrumented seams, the timeline
compile section, and the warm CLI's cold->warm acceptance loop."""

import json
from pathlib import Path

import pytest

from tpu_reductions.obs import compile as obs_compile
from tpu_reductions.obs import ledger
from tpu_reductions.utils import compile_cache


@pytest.fixture(autouse=True)
def _isolated_observatory(tmp_path, monkeypatch):
    """Each test gets its own cache dir + unarmed stores: the module
    globals (armed CompileLedger, last observation, active cache dir)
    must never leak between tests."""
    monkeypatch.delenv("TPU_REDUCTIONS_LEDGER", raising=False)
    monkeypatch.delenv("TPU_REDUCTIONS_COMPILE_LEDGER", raising=False)
    monkeypatch.delenv("TPU_REDUCTIONS_NO_COMPILE_CACHE", raising=False)
    cache_dir = tmp_path / "jc"
    cache_dir.mkdir()
    monkeypatch.setattr(compile_cache, "_active_dir", str(cache_dir))
    ledger.disarm()
    obs_compile.disarm()
    yield cache_dir
    ledger.disarm()
    obs_compile.disarm()


def _lines(path):
    return [json.loads(line) for line in
            Path(path).read_text().splitlines() if line.strip()]


# ------------------------------------------------- cache fingerprinting

def test_fingerprint_and_verdict(_isolated_observatory):
    cache = _isolated_observatory
    before = compile_cache.fingerprint()
    assert before == frozenset()
    (cache / "jit_f-abc-cache").write_bytes(b"x")
    (cache / "jit_f-abc-atime").write_bytes(b"")      # bookkeeping file
    after = compile_cache.fingerprint()
    assert after == {"jit_f-abc-cache"}
    assert compile_cache.verdict(before, after) == "cold"
    assert compile_cache.verdict(after, after) == "warm"
    assert compile_cache.verdict(frozenset(), frozenset()) == "untracked"


def test_fingerprint_empty_when_disabled(monkeypatch,
                                         _isolated_observatory):
    monkeypatch.setenv("TPU_REDUCTIONS_NO_COMPILE_CACHE", "1")
    assert compile_cache.fingerprint() == frozenset()
    assert compile_cache.active_dir() is None


def test_enable_points_jax_at_the_dir(tmp_path, monkeypatch):
    import jax
    assert compile_cache.enable(str(tmp_path / "jc2")) == \
        str(tmp_path / "jc2")
    assert jax.config.jax_compilation_cache_dir == str(tmp_path / "jc2")
    # the config.py historical entry delegates here
    from tpu_reductions.config import enable_compile_cache
    enable_compile_cache(str(tmp_path / "jc3"))
    assert jax.config.jax_compilation_cache_dir == str(tmp_path / "jc3")


# ------------------------------------------------------- span + probe

def test_compile_span_emits_cold_then_warm(tmp_path,
                                           _isolated_observatory):
    cache = _isolated_observatory
    assert ledger.arm(tmp_path / "l.jsonl")
    with obs_compile.compile_span("k6", rows=8):
        (cache / "entry-1-cache").write_bytes(b"x")   # compile landed
    with obs_compile.compile_span("k6", rows=8):
        pass                                          # served from cache
    evs = _lines(tmp_path / "l.jsonl")
    assert [e["ev"] for e in evs] == ["compile.start", "compile.end",
                                     "compile.start", "compile.end"]
    ends = [e for e in evs if e["ev"] == "compile.end"]
    assert ends[0]["verdict"] == "cold" and ends[0]["cache_new"] == 1
    assert ends[1]["verdict"] == "warm"
    assert all(e["surface"] == "k6" and e["rows"] == 8 for e in ends)
    assert obs_compile.last_observation()["verdict"] == "warm"


def test_compile_span_records_error_and_reraises(tmp_path,
                                                 _isolated_observatory):
    assert ledger.arm(tmp_path / "l.jsonl")
    store = obs_compile.arm(tmp_path / "cl.json")
    with pytest.raises(ValueError):
        with obs_compile.compile_span("k7"):
            raise ValueError("boom")
    end = _lines(tmp_path / "l.jsonl")[-1]
    assert end["ev"] == "compile.end" and "ValueError" in end["error"]
    # failed compiles never pollute the persisted cold/warm table
    assert store.rows == []


def test_probe_lower_compile_splits_and_hits_cache(tmp_path,
                                                   monkeypatch):
    """The real AOT path: a jitted fn probed twice through a real
    persistent cache — second probe must come back warm with a smaller
    compile half (the acceptance mechanism at unit scale)."""
    import jax.numpy as jnp
    import numpy as np
    monkeypatch.setattr(compile_cache, "_active_dir", None)
    assert compile_cache.enable(str(tmp_path / "jc"))
    monkeypatch.chdir(tmp_path)
    assert ledger.arm(tmp_path / "l.jsonl")
    x = np.arange(1024, dtype=np.float32)

    compiled = obs_compile.probe_lower_compile(
        lambda v: jnp.sum(v * 2), x, surface="xla")
    assert float(compiled(x)) == pytest.approx(float(x.sum() * 2))
    obs_compile.probe_lower_compile(
        lambda v: jnp.sum(v * 2), x, surface="xla")
    ends = [e for e in _lines(tmp_path / "l.jsonl")
            if e["ev"] == "compile.end"]
    assert len(ends) == 2
    assert ends[0]["verdict"] == "cold"
    assert ends[1]["verdict"] == "warm"
    assert ends[0]["lower_s"] >= 0 and ends[0]["compile_s"] > 0
    assert ends[1]["compile_s"] < ends[0]["compile_s"]


# --------------------------------------------- the persisted ledger

def test_compile_ledger_replaces_per_key_and_merges_prior(tmp_path):
    path = tmp_path / "cl.json"
    store = obs_compile.CompileLedger(str(path))
    store.record({"surface": "k6", "platform": "cpu",
                  "verdict": "cold", "dur_s": 2.0})
    store.record({"surface": "k6", "platform": "cpu",
                  "verdict": "cold", "dur_s": 1.8})
    store.record({"surface": "k6", "platform": "cpu",
                  "verdict": "warm", "dur_s": 0.1})
    data = json.loads(path.read_text())
    assert data["complete"] is False
    assert len(data["surfaces"]) == 2        # one cold + one warm row
    cold = next(r for r in data["surfaces"] if r["verdict"] == "cold")
    assert cold["dur_s"] == 1.8 and cold["count"] == 2
    store.finalize()
    assert json.loads(path.read_text())["complete"] is True
    # a NEW process merges prior rows even from a complete artifact
    # (the documented deviation: the cache it describes persists too)
    store2 = obs_compile.CompileLedger(str(path))
    assert len(store2.rows) == 2
    store2.record({"surface": "dd", "platform": "cpu",
                   "verdict": "cold", "dur_s": 3.0})
    assert len(json.loads(path.read_text())["surfaces"]) == 3


def test_arm_prefers_env_then_explicit(tmp_path, monkeypatch):
    assert obs_compile.arm() is None
    monkeypatch.setenv("TPU_REDUCTIONS_COMPILE_LEDGER",
                       str(tmp_path / "env.json"))
    store = obs_compile.arm()
    assert store is not None and store.path.endswith("env.json")
    # bare arm() keeps returning the armed store
    assert obs_compile.arm() is store


def test_compile_model_warmth_and_savings(_isolated_observatory):
    cache = _isolated_observatory
    model = obs_compile.CompileModel([
        {"surface": "k6", "verdict": "cold", "dur_s": 30.0},
        {"surface": "k6", "verdict": "warm", "dur_s": 2.0},
        {"surface": "k7", "verdict": "cold", "dur_s": 20.0},
        {"surface": "k9", "verdict": "warm", "dur_s": 1.0},
    ])
    assert model.is_warm("k6")                 # warm row observed
    assert model.is_warm("k9")
    # cold-only surface: warm iff the populated cache is still on disk
    assert not model.is_warm("k7")
    (cache / "e-cache").write_bytes(b"x")
    assert model.is_warm("k7")
    assert model.saved_s(["k6"]) == pytest.approx(28.0)
    assert model.saved_s(["k6", "k7"]) == pytest.approx(48.0)
    assert model.status(["k6", "k9"]) == "warm"
    assert model.status(["k6", "unknown"]) == "mixed"
    assert model.status(["unknown"]) == "-"
    assert model.status([]) == "-"


def test_compile_model_platform_filter(tmp_path):
    path = tmp_path / "cl.json"
    store = obs_compile.CompileLedger(str(path))
    store.record({"surface": "k6", "platform": "cpu",
                  "verdict": "warm", "dur_s": 0.1})
    store.record({"surface": "k7", "platform": "tpu",
                  "verdict": "warm", "dur_s": 0.2})
    tpu_model = obs_compile.CompileModel.from_file(str(path),
                                                   platform="tpu")
    assert tpu_model.known("k7") and not tpu_model.known("k6")


# ------------------------------------------------- instrumented seams

def test_chain_seam_emits_one_compile_event(tmp_path,
                                            _isolated_observatory):
    import numpy as np

    from tpu_reductions.ops.chain import make_chained_reduce
    from tpu_reductions.ops.registry import get_op
    assert ledger.arm(tmp_path / "l.jsonl")
    op = get_op("SUM")
    chained = make_chained_reduce(op.jnp_reduce, op, surface="xla")
    x2d = np.ones((8, 128), np.int32)
    chained(x2d, 2)
    chained(x2d, 3)      # same executable: no second span
    ends = [e for e in _lines(tmp_path / "l.jsonl")
            if e["ev"] == "compile.end"]
    assert len(ends) == 1
    assert ends[0]["surface"] == "xla" and ends[0]["rows"] == 8
    assert hasattr(chained, "jitted")      # the warm CLI's AOT handle


def test_stream_seam_emits_one_compile_event(tmp_path,
                                             _isolated_observatory):
    import numpy as np

    from tpu_reductions.ops.stream import StreamReducer
    assert ledger.arm(tmp_path / "l.jsonl")
    r = StreamReducer("SUM", "int32", 4096, chunk_bytes=2048)
    r.restore(None)
    flat = np.arange(4096, dtype=np.int32)
    r.fold(r.stage(flat, 0))
    r.fold(r.stage(flat, 1))
    ends = [e for e in _lines(tmp_path / "l.jsonl")
            if e["ev"] == "compile.end"]
    assert len(ends) == 1 and ends[0]["surface"] == "stream"


def test_serve_seam_emits_once_per_bucket(tmp_path,
                                          _isolated_observatory):
    from tpu_reductions.serve import executor as ex
    assert ledger.arm(tmp_path / "l.jsonl")
    ex._observed_buckets.clear()
    b = ex.BatchExecutor()
    b.run_batch("SUM", "int32", 256, [0])
    b.run_batch("SUM", "int32", 256, [1])      # same bucket: no span
    b.run_batch("SUM", "int32", 256, [0, 1])   # bucket 2: new span
    ends = [e for e in _lines(tmp_path / "l.jsonl")
            if e["ev"] == "compile.end"]
    assert [e["batch"] for e in ends] == [1, 2]
    assert all(e["surface"] == "serve-bucket/sum" for e in ends)


# ------------------------------------------------- timeline + report

def test_timeline_compile_section(tmp_path):
    from tpu_reductions.obs.timeline import (read_ledger, summarize,
                                             summary_markdown)
    led = tmp_path / "l.jsonl"
    with open(led, "w") as f:
        for e in [
            {"t": 0.0, "ev": "session.start", "pid": 1, "prog": "x"},
            {"t": 1.0, "ev": "compile.end", "pid": 1, "surface": "k7",
             "verdict": "cold", "dur_s": 30.0},
            {"t": 40.0, "ev": "compile.end", "pid": 1, "surface": "k7",
             "verdict": "warm", "dur_s": 1.5},
            {"t": 41.0, "ev": "warm.end", "pid": 1, "cold": 1,
             "warm": 1, "failed": 0},
            {"t": 60.0, "ev": "session.end", "pid": 1},
        ]:
            f.write(json.dumps(e) + "\n")
    events, torn = read_ledger(led)
    summary = summarize(led, events, torn)
    comp = summary["compile"]
    assert comp["compiles"] == 2
    assert comp["compile_s"] == pytest.approx(31.5)
    assert comp["warm_runs"] == 1
    rec = comp["surfaces"][0]
    assert rec["surface"] == "k7" and rec["cold_s"] == 30.0 \
        and rec["warm_s"] == 1.5 and rec["last_verdict"] == "warm"
    md = summary_markdown(summary)
    assert "compile observatory (per-surface cold/warm)" in md
    assert "| k7 | 30.000 | 1.500 | warm | 2 |" in md


def test_compile_markdown_renders_committed_artifact():
    md = obs_compile.compile_markdown({
        "complete": True,
        "surfaces": [{"surface": "k10@4", "platform": "tpu",
                      "verdict": "cold", "dur_s": 33.2,
                      "lower_s": 0.4, "compile_s": 32.8, "count": 1}],
    })
    assert "| k10@4 | tpu | cold | 0.400 | 32.800 | 33.200 | 1 |" in md
    assert "observatory: complete" in md
