"""Diagnostics tests: consistency checker, tracing, new flag wiring."""

import numpy as np
import pytest

from tpu_reductions.bench.driver import run_benchmark
from tpu_reductions.config import ReduceConfig
from tpu_reductions.utils.debug import consistency_check, trace_benchmark
from tpu_reductions.utils.qa import QAStatus


@pytest.mark.parametrize("dtype", ["int32", "float32", "float64"])
@pytest.mark.parametrize("method", ["SUM", "MIN", "MAX"])
def test_consistency_check_ok(method, dtype):
    rep = consistency_check(method, dtype, 10_000, threads=32, max_blocks=4)
    assert rep.ok, rep.describe()
    assert "[OK]" in rep.describe()


def test_consistency_report_mismatch_detection():
    rep = consistency_check("SUM", "int32", 1000)
    rep.compiled = rep.oracle + 1  # simulate a lowering bug
    assert not rep.ok and "[MISMATCH]" in rep.describe()


def test_trace_benchmark_writes_trace(tmp_path):
    import jax.numpy as jnp
    result = trace_benchmark(lambda x: x * 2, jnp.ones(16),
                             trace_dir=str(tmp_path), iterations=2)
    assert float(np.asarray(result)[0]) == 2.0
    assert any(tmp_path.rglob("*"))  # trace artifacts exist


def test_driver_check_flag():
    cfg = ReduceConfig(method="SUM", dtype="float32", n=4096, iterations=2,
                       check=True, log_file=None)
    res = run_benchmark(cfg)
    assert res.passed


def test_driver_trace_flag(tmp_path):
    cfg = ReduceConfig(method="SUM", dtype="int32", n=4096, iterations=2,
                       trace_dir=str(tmp_path / "tr"), log_file=None)
    res = run_benchmark(cfg)
    assert res.passed and any((tmp_path / "tr").rglob("*"))


def test_device_flag_valid_and_waived():
    res = run_benchmark(ReduceConfig(method="SUM", dtype="int32", n=4096,
                                     iterations=2, device=1, log_file=None))
    assert res.passed  # 8 virtual devices exist
    res2 = run_benchmark(ReduceConfig(method="SUM", dtype="int32", n=4096,
                                      iterations=2, device=99,
                                      log_file=None))
    assert res2.status == QAStatus.WAIVED


def test_qatest_quiet_console(capsys):
    cfg = ReduceConfig(method="SUM", dtype="int32", n=4096, iterations=2,
                       qatest=True, log_file=None)
    res = run_benchmark(cfg)
    assert res.passed
    out = capsys.readouterr().out
    assert "Throughput" not in out  # narrative suppressed in batch mode
