"""Trace continuity across window deaths (ISSUE 12 satellite 3): the
causal context must survive the same deaths the plan state already does
(tests/test_chaos_e2e.py). Two real `python -m tpu_reductions.sched`
invocations share one TPU_REDUCTIONS_TRACE_CTX (the chip_session
sidecar contract): the first dies at a task's watchdog-style exit 3
with a span torn open by os._exit, the second resumes the SAME trace,
marks the seam with trace.cut, and the export closes the torn spans at
the cut — the tree is never torn. Plus the `--next --emit=shell`
propagation path the chip_session loop uses."""

import json
import os
import subprocess
import sys
from pathlib import Path

from tpu_reductions.lint.grammar import TRACE_ENV
from tpu_reductions.obs import trace
from tpu_reductions.obs.timeline import read_ledger
from tpu_reductions.obs.trace_export import build_spans, chrome_trace

REPO = Path(__file__).resolve().parent.parent
WIRE_CTX = "aaaa1111:bbbb2222"   # what the chip_session sidecar reuses


def _write_flaky_task(tmp_path):
    """One sched task whose first run arms the recorder, opens a span,
    and dies via os._exit(3) — the watchdog's code, atexit bypassed, so
    both its session.start and work.start are left without closers
    (exactly the tear a real exit 3 leaves). The second run finds the
    flag file and completes."""
    (tmp_path / "task.py").write_text(
        "import os, sys\n"
        "if os.path.exists('flag'):\n"
        "    open('flaky.json', 'w').write('{\"complete\": true}')\n"
        "    sys.exit(0)\n"
        "open('flag', 'w').close()\n"
        "open('ctx.txt', 'w').write(\n"
        f"    os.environ.get({TRACE_ENV!r}, ''))\n"
        "from tpu_reductions.obs import ledger, spans\n"
        "ledger.arm_session('flaky.task')\n"
        "ctx = spans.span('work')\n"
        "ctx.__enter__()\n"
        "os._exit(3)\n")
    spec = [{"name": "flaky", "value": 10, "budget_s": 60,
             "command": f"{sys.executable} task.py",
             "artifacts": ["flaky.json"],
             "done_artifact": "flaky.json"}]
    (tmp_path / "sched_tasks.json").write_text(json.dumps(spec))


def _env(led):
    return {**os.environ,
            "PYTHONPATH": str(REPO),
            "TPU_REDUCTIONS_LEDGER": str(led),
            TRACE_ENV: WIRE_CTX,
            # untunneled: the executor's relay gate must stay out of
            # the way (this is a trace test, not a relay test)
            "TPU_REDUCTIONS_RELAY_MARKER": str(led) + ".absent"}


def _sched(tmp_path, env, *args):
    return subprocess.run(
        [sys.executable, "-m", "tpu_reductions.sched",
         "--tasks=sched_tasks.json", "--state=sched_state.json", *args],
        env=env, cwd=str(tmp_path), capture_output=True, text=True,
        timeout=120)


def test_exit3_resume_continues_trace_and_closes_torn_spans(tmp_path):
    led = tmp_path / "obs_ledger.jsonl"
    _write_flaky_task(tmp_path)
    env = _env(led)

    p1 = _sched(tmp_path, env)
    assert p1.returncode == 3, p1.stdout + p1.stderr

    # the task subprocess received a propagated context of the SAME
    # trace (the executor re-exports its own span, not the inherited
    # wire context verbatim)
    ctx = trace.decode((tmp_path / "ctx.txt").read_text())
    assert ctx is not None and ctx.trace_id == "aaaa1111"
    assert ctx.span_id != "bbbb2222"

    p2 = _sched(tmp_path, env)
    assert p2.returncode == 0, p2.stdout + p2.stderr
    state = json.loads((tmp_path / "sched_state.json").read_text())
    assert state["complete"] is True
    assert state["tasks"]["flaky"]["status"] == "done"

    events, torn = read_ledger(led)
    assert torn == 0
    # one trace across every pid of both invocations
    traced = [e for e in events if "trace" in e]
    assert traced and {e["trace"] for e in traced} == {"aaaa1111"}
    assert len({e["pid"] for e in traced}) >= 3   # 2 executors + task

    # the resume marked the seam, naming the torn task
    (cut,) = [e for e in events if e["ev"] == "trace.cut"]
    assert cut["reason"] == "window-death-resume"
    assert cut["tasks"] == ["flaky"]
    # the cut came from the SECOND invocation, after the death
    death_pid = next(e["pid"] for e in events
                     if e["ev"] == "session.start"
                     and e.get("prog") == "flaky.task")
    assert cut["pid"] != death_pid

    # no torn tree: the os._exit'd task's session + work spans close
    # AT the cut, flagged; everything else paired normally
    spans = build_spans(events)
    cut_spans = [s for s in spans if s["cut"]]
    assert {s["name"] for s in cut_spans} == {"session", "work"}
    assert all(s["pid"] == death_pid for s in cut_spans)
    assert all(s["t1"] == cut["t"] for s in cut_spans)
    # the torn work span still parents into the executor's tree: walk
    # parent ids up from `work` and land on the run-1 executor session
    by_span = {s["span"]: s for s in spans if s["span"]}
    node = next(s for s in cut_spans if s["name"] == "work")
    seen_pids = set()
    while node is not None:
        seen_pids.add(node["pid"])
        node = by_span.get(node["parent"])
    assert len(seen_pids) >= 2    # crossed the process boundary

    # and the whole thing exports as loadable Chrome-trace JSON with a
    # propagation flow arrow across that boundary
    doc = json.loads(json.dumps(chrome_trace(events)))
    assert any(e["ph"] == "s" for e in doc["traceEvents"])
    assert any(e["ph"] == "X" and e["args"].get("cut")
               for e in doc["traceEvents"])


def test_next_emit_shell_stamps_propagated_context(tmp_path):
    """The chip_session loop's interface: `sched --next --emit=shell`
    under a propagated TPU_REDUCTIONS_TRACE_CTX stamps its plan/pick
    events with the env trace id, parented under the env span — the
    shell steps and the scheduler share one tree without chip_session
    doing anything but exporting the variable."""
    led = tmp_path / "obs_ledger.jsonl"
    _write_flaky_task(tmp_path)
    p = _sched(tmp_path, _env(led), "--next", "--emit=shell")
    assert p.returncode == 0, p.stdout + p.stderr
    assert "SCHED_TASK_CMD=" in p.stdout
    events, _ = read_ledger(led)
    picks = [e for e in events if e["ev"] == "sched.pick"]
    assert picks, [e["ev"] for e in events]
    for e in picks + [e for e in events if e["ev"] == "sched.plan"]:
        assert e["trace"] == "aaaa1111"
        assert e["parent"] == "bbbb2222"
