"""Relay-liveness watchdog (utils/watchdog.py): both round-2 live
windows ended with the benchmark process hung forever on a dead tunnel
relay; the watchdog turns that into a prompt, artifact-preserving exit.
"""

import socket
import subprocess
import sys
import threading
import time

from tpu_reductions.utils.watchdog import (WATCHDOG_EXIT_CODE,
                                           relay_alive,
                                           start_relay_watchdog)


def _listener():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    s.listen(1)
    return s, s.getsockname()[1]


def test_relay_alive_probes_real_sockets():
    s, port = _listener()
    try:
        assert relay_alive(ports=(port,))
        # any-port semantics: one dead port does not mean dead
        assert relay_alive(ports=(1, port))
    finally:
        s.close()
    assert not relay_alive(ports=(port,), timeout_s=0.2)


def test_watchdog_refuses_to_arm_without_a_relay():
    """A CPU run / DRYRUN box has no relay; arming there would make the
    watchdog itself the outage."""
    assert start_relay_watchdog(ports=(1,)) is None


def test_watchdog_counts_grace_and_fires_injected_exit():
    s, port = _listener()
    fired = threading.Event()
    codes = []

    def fake_exit(code):
        codes.append(code)
        fired.set()

    try:
        stop = start_relay_watchdog(interval_s=0.05, grace=2,
                                    ports=(port,), _exit=fake_exit)
        assert stop is not None
        # alive: several intervals pass without firing
        time.sleep(0.3)
        assert not fired.is_set()
        s.close()                       # relay "dies"
        assert fired.wait(timeout=5.0)  # grace*interval later it fires
        assert codes == [WATCHDOG_EXIT_CODE]
    finally:
        s.close()
        if stop is not None:
            stop.set()


def test_watchdog_survives_transient_blips():
    """grace exists because a single slow probe is not a death: the
    consecutive-failure counter must reset when the relay answers
    again. Scripted probe sequence: blip, recover, blip, blip — never
    `grace` consecutive failures, so the watchdog must stay silent —
    then three straight failures fire it."""
    fired = threading.Event()
    script = [True,            # arming probe
              False, True,     # blip, recover (counter resets)
              False, False,    # two failures — still below grace=3
              True,            # recover again
              False, False, False]  # three straight -> fire
    calls = []

    def probe():
        calls.append(None)
        i = len(calls) - 1
        return script[i] if i < len(script) else False

    fired_at = []

    def fake_exit(code):
        # snapshot the probe count at fire time: the fake exit does not
        # stop the loop (unlike the real os._exit), so len(calls) keeps
        # growing afterwards
        fired_at.append(len(calls))
        fired.set()

    stop = start_relay_watchdog(interval_s=0.02, grace=3,
                                _probe=probe, _exit=fake_exit)
    try:
        assert stop is not None
        assert fired.wait(timeout=5.0)
        # fired exactly at the end of the scripted 3-run — i.e. the
        # earlier blips never accumulated across recoveries
        assert fired_at[0] == len(script)
    finally:
        stop.set()


def test_watchdog_hard_exits_a_wedged_process():
    """End-to-end: a subprocess whose main thread blocks forever (the
    dead-relay hang) is terminated by the watchdog with the documented
    exit code instead of hanging its caller."""
    code = (
        "import socket, threading, time, sys\n"
        "from tpu_reductions.utils.watchdog import start_relay_watchdog\n"
        "s = socket.socket(); s.bind(('127.0.0.1', 0)); s.listen(1)\n"
        "port = s.getsockname()[1]\n"
        "stop = start_relay_watchdog(interval_s=0.05, grace=2,\n"
        "                            ports=(port,))\n"
        "assert stop is not None\n"
        "s.close()\n"               # relay dies; main thread wedges:
        "time.sleep(600)\n"
    )
    t0 = time.monotonic()
    r = subprocess.run([sys.executable, "-c", code], timeout=60)
    assert r.returncode == WATCHDOG_EXIT_CODE
    assert time.monotonic() - t0 < 30


def test_maybe_arm_noop_off_tpu():
    from tpu_reductions.utils.watchdog import maybe_arm_for_tpu

    # CPU test platform: must neither arm nor exit
    assert maybe_arm_for_tpu(_exit=lambda c: (_ for _ in ()).throw(
        AssertionError("exited off-TPU"))) is None


def test_maybe_arm_exits_when_relay_already_dead(monkeypatch):
    """On a tunneled box with an unforced platform (the on-chip run), a
    dead relay means jax backend init ITSELF would hang —
    maybe_arm_for_tpu must exit with the watchdog code BEFORE the first
    jax call, not decline protection (round-2 ADVICE: autotune/calibrate
    armed the watchdog through jax.default_backend and could hang before
    the watchdog existed)."""
    import tpu_reductions.utils.watchdog as wd

    monkeypatch.setattr(wd, "tunneled_environment", lambda *a: True)
    monkeypatch.setattr(wd, "relay_alive", lambda *a, **k: False)
    monkeypatch.setattr(wd, "_forced_platforms", lambda: "")  # unforced
    codes = []
    slept = []
    out = wd.maybe_arm_for_tpu(_exit=lambda c: codes.append(c),
                               _sleep=lambda s: slept.append(s))
    assert out is None
    assert codes == [wd.WATCHDOG_EXIT_CODE]
    assert len(slept) == 1  # it re-probed before giving up


def test_maybe_arm_passes_dead_relay_when_forced_off_tpu(monkeypatch):
    """--platform=cpu on the tunneled box: device work never crosses
    the tunnel, so a dead relay must not exit the run (bench.py's CPU
    smoke path and the test suite itself run exactly this way)."""
    import tpu_reductions.utils.watchdog as wd

    monkeypatch.setattr(wd, "tunneled_environment", lambda *a: True)
    monkeypatch.setattr(wd, "relay_alive", lambda *a, **k: False)
    monkeypatch.setattr(wd, "_forced_platforms", lambda: "cpu")
    out = wd.maybe_arm_for_tpu(
        _exit=lambda c: (_ for _ in ()).throw(
            AssertionError("exited a forced-cpu run")),
        _sleep=lambda s: None)
    assert out is None


def test_maybe_arm_noop_on_untunneled_tpu_host(monkeypatch):
    """A real pod/local TPU host has no relay BY CONSTRUCTION (no
    relay script) — the watchdog must stay out of its way entirely,
    never exit-3 it at startup (docs/MULTIHOST.md hosts)."""
    import jax

    import tpu_reductions.utils.watchdog as wd

    monkeypatch.setattr(jax, "default_backend", lambda: "tpu")
    monkeypatch.setattr(wd, "tunneled_environment", lambda *a: False)
    out = wd.maybe_arm_for_tpu(
        _exit=lambda c: (_ for _ in ()).throw(
            AssertionError("killed an untunneled TPU host")))
    assert out is None


def test_relay_alive_inconclusive_on_local_resource_errors(monkeypatch):
    """EMFILE-style local failures say nothing about the tunnel: the
    probe must report alive (firing os._exit against a live tunnel
    with work in flight is the wedge hazard CLAUDE.md warns about)."""
    import socket as socket_mod

    import tpu_reductions.utils.watchdog as wd

    def raise_emfile(*a, **k):
        raise OSError(24, "Too many open files")

    monkeypatch.setattr(wd.socket, "create_connection", raise_emfile)
    assert wd.relay_alive(ports=(1,)) is True

    def refused(*a, **k):
        raise ConnectionRefusedError()

    monkeypatch.setattr(wd.socket, "create_connection", refused)
    assert wd.relay_alive(ports=(1,)) is False


def test_maybe_arm_arms_when_relay_alive(monkeypatch):
    import jax

    import tpu_reductions.utils.watchdog as wd

    monkeypatch.setattr(jax, "default_backend", lambda: "tpu")
    monkeypatch.setattr(wd, "tunneled_environment", lambda *a: True)
    s, port = _listener()
    monkeypatch.setattr(wd, "RELAY_PORTS", (port,))
    try:
        stop = wd.maybe_arm_for_tpu(
            _exit=lambda c: (_ for _ in ()).throw(
                AssertionError("exited with relay alive")))
        assert stop is not None
        stop.set()
    finally:
        s.close()


def test_inconclusive_probes_counted_in_exit_report():
    """Satellite (ISSUE 2): EMFILE-class probes still reset the dead
    counter (firing on them would be the wedge hazard), but they are
    COUNTED and surfaced in the exit-3 stderr report instead of
    silently vanishing — the postmortem must see a probe loop that
    spent its window starved of fds."""
    fired = threading.Event()
    script = ["alive",                       # arming probe
              "inconclusive", "inconclusive",  # counted, not dead
              "dead", "inconclusive",        # resets the dead counter
              "dead", "dead"]                # grace=2 -> fire
    calls = []

    def probe():
        calls.append(None)
        i = len(calls) - 1
        return script[i] if i < len(script) else "dead"

    def fake_exit(code):
        fired.set()

    import sys as _sys
    captured = []

    class _Cap:
        def write(self, s):
            captured.append(s)

        def flush(self):
            pass

    real_err = _sys.stderr
    _sys.stderr = _Cap()
    try:
        stop = start_relay_watchdog(interval_s=0.02, grace=2,
                                    _probe=probe, _exit=fake_exit)
        assert stop is not None
        assert fired.wait(timeout=5.0)
    finally:
        stop.set()
        _sys.stderr = real_err
    text = "".join(captured)
    assert "relay is gone" in text
    assert "3 inconclusive probe(s)" in text


def test_env_overrides_point_probe_at_fake_relay(monkeypatch):
    """TPU_REDUCTIONS_RELAY_PORTS / _RELAY_MARKER are the chaos
    harness's seam: the probe and the tunneled-environment check must
    honor them over the baked-in defaults."""
    import tpu_reductions.utils.watchdog as wd

    s, port = _listener()
    try:
        monkeypatch.setenv("TPU_REDUCTIONS_RELAY_PORTS", str(port))
        assert wd.relay_alive() is True
        assert wd.resolved_ports() == (port,)
    finally:
        s.close()
    monkeypatch.setenv("TPU_REDUCTIONS_RELAY_MARKER", __file__)
    assert wd.tunneled_environment() is True
    monkeypatch.setenv("TPU_REDUCTIONS_RELAY_MARKER",
                       __file__ + ".does-not-exist")
    assert wd.tunneled_environment() is False


# The chip-session step-machinery contracts (rc=3 abort with
# artifacts committed, relay-death-between-steps, budgets, the
# window-summary trap) are rehearsed in tests/test_chip_session.py
# via the script's sourceable CHIP_SESSION_LIB mode — the former
# text-slicing extraction of step() lived here and broke whenever
# the script's layout moved.
