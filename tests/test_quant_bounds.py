"""Property tests of the quantized collective error contract
(collectives/quant.py; ISSUE 10 satellite): for every (bits, op, dtype)
the suite registers, the measured |quantized - oracle| stays under the
DECLARED bound (`quant_error_bound`) across the in-process rank ladder,
MIN/MAX over quantized keys is EXACT (bound 0), and the committed
accuracy-vs-bandwidth artifact (examples/rank_scaling/quant_curve.json,
ranks 2..64 in subprocess) honors the same contract — so the curve the
report publishes can never claim a bound the code does not meet."""

import json
from pathlib import Path

import jax.numpy as jnp
import numpy as np
import pytest

from tpu_reductions.collectives.quant import (KEY_BITS, MINMAX_DTYPES,
                                              QUANT_BITS, QUANT_BLOCK,
                                              SUM_DTYPES, coarse_key,
                                              levels,
                                              make_quant_key_minmax_all_reduce,
                                              make_quant_sum_all_reduce,
                                              monotone_key32,
                                              np_monotone_key32,
                                              quant_error_bound,
                                              quant_supported)
from tpu_reductions.ops.dd_reduce import (host_key_decode,
                                          host_key_encode, host_split)
from tpu_reductions.parallel.collectives import shard_payload
from tpu_reductions.parallel.mesh import build_mesh

RANKS = (2, 4, 8)   # the conftest mesh's in-process ladder; the
                    # committed curve extends it to 64 in subprocess


def _sum_payload(k: int, per: int, seed: int) -> np.ndarray:
    rng = np.random.default_rng([seed, k])
    return rng.normal(scale=50.0, size=k * per).astype(np.float64)


@pytest.mark.parametrize("k", RANKS)
@pytest.mark.parametrize("bits", QUANT_BITS)
def test_quant_sum_f32_within_declared_bound(bits, k):
    """SUM/float32 at every registered width: measured error under the
    declared error-feedback bound, replicated result finite."""
    mesh = build_mesh(num_devices=k)
    per = k * QUANT_BLOCK
    x = _sum_payload(k, per, seed=1).astype(np.float32)
    fn = make_quant_sum_all_reduce(mesh, "ranks", bits=bits,
                                   dtype="float32")
    got = np.asarray(fn(shard_payload(x, mesh, "ranks")),
                     dtype=np.float64)
    exact = x.reshape(k, per).astype(np.float64).sum(axis=0)
    bound = quant_error_bound("SUM", "float32", bits, k,
                              float(np.abs(x).max()))
    assert float(np.abs(got - exact).max()) <= bound
    # the bound is a real constraint, not vacuous: at 4 bits the coarse
    # wire must actually err more than f32 psum noise would
    if bits == 4:
        assert float(np.abs(got - exact).max()) > 1e-3


@pytest.mark.parametrize("k", RANKS)
@pytest.mark.parametrize("bits", QUANT_BITS)
def test_quant_sum_bf16_within_declared_bound(bits, k):
    """SUM/bfloat16: f32 accumulation under the quantized wire, output
    cast's half-ulp folded into the declared bound."""
    mesh = build_mesh(num_devices=k)
    per = k * QUANT_BLOCK
    xbf = jnp.asarray(_sum_payload(k, per, seed=2),
                      dtype=jnp.bfloat16)
    x = np.asarray(xbf.astype(jnp.float32), dtype=np.float64)
    fn = make_quant_sum_all_reduce(mesh, "ranks", bits=bits,
                                   dtype="bfloat16")
    got = np.asarray(
        fn(shard_payload(np.asarray(xbf), mesh, "ranks")).astype(
            jnp.float32), dtype=np.float64)
    exact = x.reshape(k, per).sum(axis=0)
    bound = quant_error_bound("SUM", "bfloat16", bits, k,
                              float(np.abs(x).max()))
    assert float(np.abs(got - exact).max()) <= bound


@pytest.mark.parametrize("k", RANKS)
@pytest.mark.parametrize("bits", QUANT_BITS)
def test_quant_sum_dd_within_declared_bound(bits, k):
    """SUM/float64 (dd pair planes): the host-split hi/lo planes collapse
    on device in f32 — no f64 near the TPU — and the combined error
    stays under the declared bound's added 2^-22 collapse term."""
    mesh = build_mesh(num_devices=k)
    per = k * QUANT_BLOCK
    x = _sum_payload(k, per, seed=3)
    hi, lo = host_split(x)
    fn = make_quant_sum_all_reduce(mesh, "ranks", bits=bits,
                                   dtype="float64")
    out_hi, out_lo = fn(shard_payload(hi, mesh, "ranks"),
                        shard_payload(lo, mesh, "ranks"))
    got = (np.asarray(out_hi, dtype=np.float64)
           + np.asarray(out_lo, dtype=np.float64))
    exact = x.reshape(k, per).sum(axis=0)
    bound = quant_error_bound("SUM", "float64", bits, k,
                              float(np.abs(x).max()))
    assert float(np.abs(got - exact).max()) <= bound


def _minmax_payload(k: int, per: int, seed: int) -> np.ndarray:
    # negatives, near-ties and exact duplicates: the cases that break a
    # NON-order-preserving quantization
    rng = np.random.default_rng([seed, k])
    x = rng.normal(scale=10.0, size=k * per)
    dup = rng.integers(0, k * per, size=per // 2)
    x[dup] = x[dup[::-1]]
    return x


@pytest.mark.parametrize("k", RANKS)
@pytest.mark.parametrize("method", ["MIN", "MAX"])
@pytest.mark.parametrize("bits", KEY_BITS)
def test_quant_key_minmax_f32_is_exact(bits, method, k):
    """MIN/MAX over order-preserving quantized f32 keys: bit-exact
    against the numpy oracle at every registered width — the curve's
    zero-error rows (quant_error_bound returns 0.0 here)."""
    mesh = build_mesh(num_devices=k)
    per = 1024
    x = _minmax_payload(k, per, seed=4).astype(np.float32)
    fn = make_quant_key_minmax_all_reduce(method, mesh, "ranks",
                                          bits=bits, dtype="float32")
    got = np.asarray(fn(shard_payload(x, mesh, "ranks")))
    oracle = getattr(np, method.lower())(x.reshape(k, per), axis=0)
    assert quant_error_bound(method, "float32", bits, k, 10.0) == 0.0
    np.testing.assert_array_equal(got, oracle)


@pytest.mark.parametrize("k", RANKS)
@pytest.mark.parametrize("method", ["MIN", "MAX"])
@pytest.mark.parametrize("bits", KEY_BITS)
def test_quant_key_minmax_dd_is_exact(bits, method, k):
    """MIN/MAX over f64 key pairs: the coarse phase rides the hi plane,
    the resolve phases are the exact lexicographic two-phase — decode
    of the winning pair is bit-exact f64."""
    mesh = build_mesh(num_devices=k)
    per = 1024
    x = _minmax_payload(k, per, seed=5)
    k_hi, k_lo = host_key_encode(x)
    fn = make_quant_key_minmax_all_reduce(method, mesh, "ranks",
                                          bits=bits, dtype="float64")
    m_hi, m_lo = fn(shard_payload(k_hi, mesh, "ranks"),
                    shard_payload(k_lo, mesh, "ranks"))
    got = host_key_decode(np.asarray(m_hi), np.asarray(m_lo))
    oracle = getattr(np, method.lower())(x.reshape(k, per), axis=0)
    np.testing.assert_array_equal(got, oracle)


def test_coarse_key_is_order_preserving():
    """The exactness argument's load-bearing lemma: monotone_key32
    orders like f32, and the arithmetic-shift coarse key never inverts
    an order (non-strict monotonicity at every registered width)."""
    rng = np.random.default_rng(6)
    # no -0.0: np.sort ties it with +0.0 while the key orders them
    # strictly (-0.0 < +0.0) — a finer order, not an inversion
    x = np.sort(np.concatenate([
        rng.normal(scale=1e3, size=4096),
        [-np.inf, np.inf, 0.0]]).astype(np.float32))
    keys = np_monotone_key32(x)
    assert (np.diff(keys) >= 0).all()
    assert np.array_equal(keys, np.asarray(monotone_key32(jnp.asarray(x))))
    for bits in KEY_BITS:
        coarse = np.asarray(coarse_key(jnp.asarray(keys), bits),
                            dtype=np.int32)
        assert (np.diff(coarse) >= 0).all()
        # and the carrier really is b-bit: values fit the signed range
        assert coarse.min() >= -(1 << (bits - 1))
        assert coarse.max() < (1 << (bits - 1))


def test_quant_supported_matrix_and_levels():
    """The support predicate is the single gate (config fail-fast and
    the selector both call it): exactly the registered matrix, nothing
    else — and the step budget the SUM bound divides by is the symmetric
    level count."""
    for dtype in SUM_DTYPES:
        for bits in QUANT_BITS:
            assert quant_supported("SUM", dtype, bits)
    for dtype in MINMAX_DTYPES:
        for bits in KEY_BITS:
            assert quant_supported("MIN", dtype, bits)
            assert quant_supported("MAX", dtype, bits)
    assert not quant_supported("SUM", "int32", 8)       # no lossy story
    assert not quant_supported("SUM", "float32", 5)     # unregistered width
    assert not quant_supported("MIN", "bfloat16", 8)    # keys are f32/f64
    assert not quant_supported("MAX", "float32", 4)     # 4b keys unregistered
    assert (levels(4), levels(8), levels(16)) == (7, 127, 32767)


def test_committed_quant_curve_honors_declared_bounds():
    """The COMMITTED artifact (ranks 2..64, beyond the in-process mesh)
    obeys the same contract this file pins at 2..8: every row measured
    under its declared bound, MIN/MAX rows exact, and the flagship
    wire-reduction claim (>= 3.5x at int8/f32 SUM vs the exact ring)
    present at every rank count."""
    path = (Path(__file__).resolve().parent.parent / "examples"
            / "rank_scaling" / "quant_curve.json")
    data = json.loads(path.read_text())
    assert data["complete"] is True
    rows = data["rows"]
    assert {r["ranks"] for r in rows} >= {2, 4, 8, 16, 32, 64}
    for r in rows:
        assert r["status"] == "PASSED", r
        assert r["max_err"] <= r["bound"], r
        if r["method"] in ("MIN", "MAX"):
            assert r["bound"] == 0.0 and r["exact"], r
    q8f32 = [r for r in rows if (r["method"], r["dtype"], r["bits"])
             == ("SUM", "float32", 8)]
    assert q8f32 and all(r["wire_reduction"] >= 3.5 for r in q8f32)
