"""Hang-proof preflight (utils/preflight.py): a sacrificial subprocess
classifies the chip LIVE / NO_RELAY / STALLED / WEDGED under a hard
timeout — the parent never blocks on a JAX call, so the classification
itself can never become the hang it exists to prevent."""

import json
import time

import pytest

from tpu_reductions.faults import inject
from tpu_reductions.faults.relay import FakeRelay
from tpu_reductions.utils import preflight
from tpu_reductions.utils.jsonio import atomic_json_dump


@pytest.fixture
def tunneled(monkeypatch, tmp_path):
    """A tunneled environment pointed at a FakeRelay, with an isolated
    health file; yields the relay."""
    marker = tmp_path / "relay.marker"
    marker.write_text("tunneled\n")
    health = tmp_path / "health.json"
    with FakeRelay() as relay:
        monkeypatch.setenv("TPU_REDUCTIONS_RELAY_MARKER", str(marker))
        monkeypatch.setenv("TPU_REDUCTIONS_RELAY_PORTS", str(relay.port))
        monkeypatch.setenv("TPU_REDUCTIONS_HEALTH_FILE", str(health))
        monkeypatch.setenv("TPU_REDUCTIONS_PREFLIGHT_PLATFORM", "cpu")
        monkeypatch.delenv(inject.ENV_VAR, raising=False)
        yield relay


def test_live_chip_classifies_live(tunneled, monkeypatch):
    record = preflight.run_preflight(timeout_s=60.0)
    assert record["verdict"] == preflight.LIVE
    assert record["relay"] == "alive"
    # the verdict persisted (atomic, utils/jsonio) and reads back fresh
    assert preflight.read_health()["verdict"] == preflight.LIVE


def test_scripted_wedge_classifies_wedged_without_parent_jax(
        tunneled, monkeypatch):
    """The acceptance scenario: the `preflight.probe` fault point fires
    in the SACRIFICIAL child (before its jax import) and wedges it —
    exactly what a wedged device lease does to discovery — while the
    relay services connections normally. The parent classifies WEDGED
    within the hard timeout, never touching a JAX backend itself."""
    monkeypatch.setenv(inject.ENV_VAR, json.dumps(
        {"preflight.probe": {"action": "stall", "seconds": 60}}))
    t0 = time.monotonic()
    record = preflight.run_preflight(timeout_s=2.0)
    assert record["verdict"] == preflight.WEDGED
    assert record["relay"] == "alive"
    assert time.monotonic() - t0 < 30   # bounded, never child-duration
    assert "hung past" in record["detail"]


def test_stalled_relay_classifies_stalled(tunneled, monkeypatch):
    """Ports accept but connections are held unserviced (the relay
    `stall` behavior): discovery hangs AND the service probe hangs —
    STALLED, not WEDGED."""
    tunneled.force("stall")
    monkeypatch.setenv(inject.ENV_VAR, json.dumps(
        {"preflight.probe": {"action": "stall", "seconds": 60}}))
    record = preflight.run_preflight(timeout_s=2.0)
    assert record["verdict"] == preflight.STALLED


def test_dead_relay_classifies_no_relay_without_spawning(tunneled):
    tunneled.force("refuse")
    time.sleep(0.15)   # let the listener actually close
    t0 = time.monotonic()
    record = preflight.run_preflight(timeout_s=60.0)
    assert record["verdict"] == preflight.NO_RELAY
    assert time.monotonic() - t0 < 10   # no discovery subprocess paid
    assert "not attempted" in record["detail"]


def test_read_health_rejects_stale_and_garbage(tmp_path, monkeypatch):
    health = tmp_path / "health.json"
    monkeypatch.setenv("TPU_REDUCTIONS_HEALTH_FILE", str(health))
    assert preflight.read_health() is None          # absent
    health.write_text("{not json")
    assert preflight.read_health() is None          # unparseable
    atomic_json_dump(health, {"verdict": "WEDGED",
                              "ts": time.time() - 9999})
    assert preflight.read_health() is None          # stale (TTL)
    atomic_json_dump(health, {"verdict": "WEDGED", "ts": time.time()})
    assert preflight.read_health()["verdict"] == "WEDGED"


def test_gate_verdict_modes(tmp_path, monkeypatch):
    health = tmp_path / "health.json"
    monkeypatch.setenv("TPU_REDUCTIONS_HEALTH_FILE", str(health))
    atomic_json_dump(health, {"verdict": "STALLED", "ts": time.time()})
    monkeypatch.delenv("TPU_REDUCTIONS_PREFLIGHT", raising=False)
    assert preflight.gate_verdict() == "STALLED"    # fresh file answers
    monkeypatch.setenv("TPU_REDUCTIONS_PREFLIGHT", "0")
    assert preflight.gate_verdict() is None         # gate disabled
    # no fresh file + passive default: no discovery subprocess is paid
    monkeypatch.delenv("TPU_REDUCTIONS_PREFLIGHT", raising=False)
    health.unlink()
    assert preflight.gate_verdict() is None


def test_maybe_arm_exits_4_on_fresh_wedge_verdict(tmp_path, monkeypatch):
    """The pre-JAX wedge gate (watchdog.maybe_arm_for_tpu): on the
    tunneled box with a fresh STALLED/WEDGED health verdict, the first
    jax call can only hang — exit 4 BEFORE it, unless the run is
    explicitly forced off-TPU (whose device work never crosses the
    tunnel)."""
    import tpu_reductions.utils.watchdog as wd

    health = tmp_path / "health.json"
    monkeypatch.setenv("TPU_REDUCTIONS_HEALTH_FILE", str(health))
    atomic_json_dump(health, {"verdict": "WEDGED", "ts": time.time()})
    monkeypatch.setattr(wd, "tunneled_environment", lambda *a: True)
    monkeypatch.setattr(wd, "relay_alive", lambda *a, **k: True)
    monkeypatch.setattr(wd, "_forced_platforms", lambda: "")  # unforced
    codes = []
    out = wd.maybe_arm_for_tpu(_exit=lambda c: codes.append(c),
                               _sleep=lambda s: None)
    assert out is None
    assert codes == [wd.HANG_EXIT_CODE]

    # forced off-TPU: the wedge cannot reach a cpu run — proceed
    monkeypatch.setattr(wd, "_forced_platforms", lambda: "cpu")
    codes.clear()
    wd.maybe_arm_for_tpu(_exit=lambda c: codes.append(c),
                         _sleep=lambda s: None)
    assert codes == []


def test_cli_exit_codes_map_verdicts(tunneled, monkeypatch, capsys):
    """0=LIVE, 3=NO_RELAY, 4=STALLED/WEDGED — the vocabulary
    scripts/await_window.sh keys its firing decision on."""
    assert preflight.main(["--timeout=60"]) == 0
    tunneled.force("refuse")
    time.sleep(0.15)
    assert preflight.main(["--timeout=60"]) == 3
    tunneled.force("accept")
    time.sleep(0.3)    # let the refuse-phase listener rebind
    monkeypatch.setenv(inject.ENV_VAR, json.dumps(
        {"preflight.probe": {"action": "stall", "seconds": 60}}))
    assert preflight.main(["--timeout=2"]) == 4
    out = capsys.readouterr().out
    assert "preflight: LIVE" in out and "preflight: NO_RELAY" in out
