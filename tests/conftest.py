"""Test harness configuration.

Forces an 8-device virtual CPU platform BEFORE any test imports touch jax —
the multi-device simulation path the reference never had (its distributed
testing was "run on Blue Gene and eyeball rank-0 stdout", SURVEY.md §4).
Pallas kernels run in interpreter mode on CPU (pallas_reduce picks this up
automatically from the backend).

Note: the axon TPU plugin in this image overrides the JAX_PLATFORMS env
var, so the platform must be forced through jax.config instead.
"""

import os

# harmless on the config path, but kept for plain-jaxlib environments
os.environ.setdefault("JAX_PLATFORMS", "cpu")
# Pre-0.4.38 jax has no jax_num_cpu_devices config option; the XLA flag
# is the portable way to get 8 virtual CPU devices and must be set
# before jax initializes its backends.
if "--xla_force_host_platform_device_count" not in os.environ.get(
        "XLA_FLAGS", ""):
    os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                               + " --xla_force_host_platform_device_count=8")

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
try:
    jax.config.update("jax_num_cpu_devices", 8)
except AttributeError:  # older jax: the XLA_FLAGS path above covers it
    pass

# f64 configs need x64; enabling it globally keeps tests order-independent.
jax.config.update("jax_enable_x64", True)


import pytest  # noqa: E402


@pytest.fixture
def stable_chained_timing(monkeypatch):
    """Deterministic chained slopes for CLI-shape tests (round-4 judge,
    weak #2): at test-scale n the slope's in-program signal is
    microseconds, so a loaded host can legitimately measure a
    non-positive median — the product then (correctly) WAIVEs, and a
    test asserting PASSED flakes. This wrapper runs the REAL chained
    machinery every time (trip counts, data-dependent chain, both
    k-points) and substitutes a nominal positive slope ONLY when host
    noise swamped it. The product's WAIVE-on-noise guard keeps its own
    deterministic coverage in
    tests/test_driver.py::test_noise_swamped_chained_slope_waives."""
    import types

    from tpu_reductions.utils import timing as timing_mod

    real = timing_mod.time_chained

    def stabilized(*args, **kwargs):
        sw = real(*args, **kwargs)
        if sw.median_s <= 0 or sw.average_s <= 0:
            return types.SimpleNamespace(average_s=1e-4, median_s=1e-4,
                                         samples=[1e-4])
        return sw

    monkeypatch.setattr(timing_mod, "time_chained", stabilized)
