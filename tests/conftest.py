"""Test harness configuration.

Forces an 8-device virtual CPU platform BEFORE any test imports touch jax —
the multi-device simulation path the reference never had (its distributed
testing was "run on Blue Gene and eyeball rank-0 stdout", SURVEY.md §4).
Pallas kernels run in interpreter mode on CPU (pallas_reduce picks this up
automatically from the backend).

Note: the axon TPU plugin in this image overrides the JAX_PLATFORMS env
var, so the platform must be forced through jax.config instead.
"""

import os

# harmless on the config path, but kept for plain-jaxlib environments
os.environ.setdefault("JAX_PLATFORMS", "cpu")

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_num_cpu_devices", 8)

# f64 configs need x64; enabling it globally keeps tests order-independent.
jax.config.update("jax_enable_x64", True)
