"""Multi-host (DCN-analog) path: initialize_distributed unit tests with
a mocked jax.distributed, real chip-granularity CO mode, and REAL two-
and four-process gloo collective runs (the four-process one on the f64
key-pair path) — the coverage the reference never had for its mpirun
tier (it validated multi-node by running on Blue Gene, SURVEY.md §4
"real cluster only")."""

from __future__ import annotations

import os
import subprocess
import sys

import pytest

from tpu_reductions.parallel import mesh as mesh_mod

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# --------------------------- initialize_distributed ----------------------

class _SpyInit:
    def __init__(self):
        self.calls = []

    def __call__(self, **kw):
        self.calls.append(kw)


def test_initialize_distributed_single_process_noop(monkeypatch):
    spy = _SpyInit()
    monkeypatch.setattr(mesh_mod.jax.distributed, "initialize", spy)
    assert mesh_mod.initialize_distributed() is False
    assert mesh_mod.initialize_distributed(num_processes=1) is False
    assert spy.calls == []


def test_initialize_distributed_forwards_launch_args(monkeypatch):
    spy = _SpyInit()
    monkeypatch.setattr(mesh_mod.jax.distributed, "initialize", spy)
    monkeypatch.setattr(mesh_mod, "_distributed_client_active",
                        lambda: False)
    assert mesh_mod.initialize_distributed(
        coordinator_address="10.0.0.1:8476", num_processes=4,
        process_id=2) is True
    assert spy.calls == [dict(coordinator_address="10.0.0.1:8476",
                              num_processes=4, process_id=2)]


def test_initialize_distributed_already_initialized_noop(monkeypatch):
    """Calling jax.distributed.initialize twice raises; the guard must
    no-op instead (the docstring's promise, now actually implemented)."""
    spy = _SpyInit()
    monkeypatch.setattr(mesh_mod.jax.distributed, "initialize", spy)
    monkeypatch.setattr(mesh_mod, "_distributed_client_active",
                        lambda: True)
    assert mesh_mod.initialize_distributed(
        coordinator_address="x:1", num_processes=2, process_id=0) is False
    assert spy.calls == []


# ------------------------------ CO granularity ---------------------------

class _FakeTpuDev:
    """Stub with the attributes real TpuDevice objects expose."""

    def __init__(self, pid, coords, core):
        self.process_index = pid
        self.coords = coords
        self.core_on_chip = core

    def __repr__(self):
        return f"tpu(p{self.process_index},{self.coords},c{self.core_on_chip})"


def test_co_mode_picks_one_core_per_chip():
    """Dual-TensorCore generations (v2/v3/v5p): CO keeps core 0 of every
    chip — the true BG/L 1-rank-per-node analog (ccni_vn.sh:6)."""
    devs = [_FakeTpuDev(0, (x, 0, 0), c) for x in range(4) for c in (0, 1)]
    picked = mesh_mod.coarsen_to_chips(devs)
    assert len(picked) == 4
    assert all(d.core_on_chip == 0 for d in picked)
    assert sorted(d.coords for d in picked) == [(x, 0, 0) for x in range(4)]


def test_co_mode_single_core_chips_unchanged():
    """Megacore generations (v4/v5e): one device per chip already — CO
    == VN, as on a single-core node."""
    devs = [_FakeTpuDev(0, (x, 0, 0), 0) for x in range(4)]
    assert mesh_mod.coarsen_to_chips(devs) == devs


def test_co_mode_multi_host_chips_distinct():
    """Chips on different hosts share coords values but are distinct
    chips: the (process, slice, coords) key must not merge them."""
    devs = [_FakeTpuDev(p, (0, 0, 0), c) for p in (0, 1) for c in (0, 1)]
    picked = mesh_mod.coarsen_to_chips(devs)
    assert len(picked) == 2
    assert sorted(d.process_index for d in picked) == [0, 1]


def test_co_mode_cpu_simulation_halves():
    """Virtual CPU devices carry no chip topology: CO falls back to the
    documented every-other-device SIMULATION of the VN->CO halving."""
    m = mesh_mod.build_mesh(mode="co")
    import jax
    assert m.shape[m.axis_names[0]] == max(1, len(jax.devices()) // 2)


# --------------------------- real two-process run ------------------------

def _spawn(port: int, pid: int, *extra: str, method: str = "SUM",
           dtype: str = "int", n: int = 65536, retries: int = 2,
           devices: int = 4, num_processes: int = 2,
           env_extra: dict | None = None) -> subprocess.Popen:
    return subprocess.Popen(
        [sys.executable, "-m", "tpu_reductions.bench.collective_driver",
         f"--method={method}", f"--type={dtype}", f"--n={n}",
         f"--retries={retries}", "--platform=cpu",
         f"--devices={devices}", f"--coordinator=127.0.0.1:{port}",
         f"--num-processes={num_processes}", f"--process-id={pid}",
         *extra],
        cwd=REPO, stdout=subprocess.PIPE, stderr=subprocess.PIPE,
        text=True,
        env={**os.environ, "XLA_FLAGS": "",    # drop conftest's 8-dev flag
             **(env_extra or {})})


def test_two_process_collective_cli():
    """The DCN-analog transport for real: two OS processes, gloo over
    localhost, one global 4-device mesh, verified SUM, rank-0-only
    reporting (reduce.c:68,81,95)."""
    port = 20000 + (os.getpid() % 10000)
    p0 = _spawn(port, 0)
    p1 = _spawn(port, 1)
    out0, err0 = p0.communicate(timeout=240)
    out1, err1 = p1.communicate(timeout=240)
    assert p0.returncode == 0, (out0, err0)
    assert p1.returncode == 0, (out1, err1)
    assert "&&&& RUNNING tpu_reductions.collective" in out0
    assert "&&&& tpu_reductions.collective PASSED" in out0
    rows = [ln for ln in out0.splitlines()
            if ln.startswith("INT SUM 4 ")]
    assert len(rows) == 2, out0        # --retries=2 measurement rows
    # rank-0-only reporting: process 1 prints nothing of ours (gloo's
    # own connection banner is transport noise, not framework output)
    ours = [ln for ln in out1.splitlines()
            if ln.strip() and not ln.startswith("[Gloo]")]
    assert ours == [], out1


def test_two_process_interleaved_scatter_verifies():
    """Interleaved device mapping scatters one process's shards across
    the global order; scatter-mode verification must line each local
    shard up with its true global slice (the selector path in
    collectives.local_view_and_selection), not assume contiguity."""
    port = 20000 + ((os.getpid() + 1) % 10000)
    extra = ("--mapping=interleaved", "--rooted")
    p0 = _spawn(port, 0, *extra)
    p1 = _spawn(port, 1, *extra)
    out0, err0 = p0.communicate(timeout=240)
    out1, err1 = p1.communicate(timeout=240)
    assert p0.returncode == 0, (out0, err0)
    assert p1.returncode == 0, (out1, err1)
    assert "&&&& tpu_reductions.collective PASSED" in out0


def test_four_process_f64_pair_collective():
    """Four OS processes over gloo — the rank-count scaling axis the
    reference swept on Blue Gene (submit_all.sh:3-4) — running the f64
    key-pair MIN collective (TPU_REDUCTIONS_FORCE_DD=1 runs the TPU
    wire encoding on the CPU mesh): the exact-selection pair path must
    verify when its planes are scattered across four separate
    processes, and only rank 0 reports."""
    port = 20000 + ((os.getpid() + 2) % 10000)
    force = {"TPU_REDUCTIONS_FORCE_DD": "1"}
    procs = [_spawn(port, pid, method="MIN", dtype="double", n=16384,
                    retries=1, devices=8, num_processes=4,
                    env_extra=force)
             for pid in range(4)]
    outs = [p.communicate(timeout=300) for p in procs]
    for p, (out, err) in zip(procs, outs):
        assert p.returncode == 0, (out, err)
    out0 = outs[0][0]
    assert "&&&& tpu_reductions.collective PASSED" in out0
    rows = [ln for ln in out0.splitlines()
            if ln.startswith("DOUBLE MIN 8 ")]
    assert rows, out0
    for out, _ in outs[1:]:
        ours = [ln for ln in out.splitlines()
                if ln.strip() and not ln.startswith("[Gloo]")]
        assert ours == [], out


def test_indivisible_devices_per_process_rejected():
    """--devices must split evenly across processes; the error speaks in
    the user's own flag values (config._apply_platform)."""
    p = subprocess.run(
        [sys.executable, "-m", "tpu_reductions.bench.collective_driver",
         "--method=SUM", "--type=int", "--platform=cpu", "--devices=3",
         "--coordinator=127.0.0.1:1", "--num-processes=2",
         "--process-id=0"],
        cwd=REPO, capture_output=True, text=True, timeout=120,
        env={**os.environ, "XLA_FLAGS": ""})
    assert p.returncode != 0
    # the EXPLANATION must reach the user, not just the argv echo
    assert "must divide" in p.stderr, (p.stdout, p.stderr)
    assert "--devices=3" in p.stderr
