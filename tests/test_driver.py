"""L4 driver tests: the full self-verifying benchmark flow on CPU."""

import numpy as np
import pytest

from tpu_reductions.bench.driver import main, run_benchmark
from tpu_reductions.config import ReduceConfig
from tpu_reductions.utils.logging import BenchLogger
from tpu_reductions.utils.qa import QAStatus


def _cfg(**kw):
    base = dict(method="SUM", dtype="int32", n=4096, iterations=3, warmup=1,
                log_file=None, master_log=None)
    base.update(kw)
    return ReduceConfig(**base)


@pytest.mark.parametrize("dtype", ["int32", "float32", "float64"])
@pytest.mark.parametrize("method", ["SUM", "MIN", "MAX"])
def test_run_benchmark_all_configs(method, dtype):
    # the 9 runTest instantiations (reduction.cpp:161-200) in one driver
    res = run_benchmark(_cfg(method=method, dtype=dtype))
    assert res.status == QAStatus.PASSED, res.to_dict()
    assert res.gbps > 0 and res.iterations == 3


def test_run_benchmark_xla_backend():
    res = run_benchmark(_cfg(backend="xla", method="MAX", dtype="float32"))
    assert res.passed


def test_waived_kernel():
    # kernels 0-5 -> WAIVED (reduction_kernel.cu:278-289 emptied cases)
    res = run_benchmark(_cfg(kernel=3))
    assert res.status == QAStatus.WAIVED


def test_two_pass_and_cpufinal():
    for kw in [dict(kernel=7), dict(kernel=7, cpu_final=True),
               dict(cpu_final=True)]:
        res = run_benchmark(_cfg(method="MIN", dtype="float32", n=100_000,
                                 threads=16, max_blocks=8, **kw))
        assert res.passed, res.to_dict()


def test_throughput_line_in_logs(tmp_path):
    app = tmp_path / "app.txt"
    master = tmp_path / "master.txt"
    logger = BenchLogger(str(app), str(master))
    run_benchmark(_cfg(), logger=logger)
    assert "Reduction, Throughput = " in master.read_text()


def test_cli_main_exit_codes(tmp_path, monkeypatch):
    monkeypatch.chdir(tmp_path)
    code = main(["--method=SUM", "--type=int", "--n=4096",
                 "--iterations=2", "--logfile", str(tmp_path / "r.txt")])
    assert code == 0
