"""L4 driver tests: the full self-verifying benchmark flow on CPU."""

import numpy as np
import pytest

from tpu_reductions.bench.driver import main, run_benchmark
from tpu_reductions.config import ReduceConfig
from tpu_reductions.utils.logging import BenchLogger
from tpu_reductions.utils.qa import QAStatus


def _cfg(**kw):
    base = dict(method="SUM", dtype="int32", n=4096, iterations=3, warmup=1,
                log_file=None, master_log=None)
    base.update(kw)
    return ReduceConfig(**base)


@pytest.mark.parametrize("dtype", ["int32", "float32", "float64"])
@pytest.mark.parametrize("method", ["SUM", "MIN", "MAX"])
def test_run_benchmark_all_configs(method, dtype):
    # the 9 runTest instantiations (reduction.cpp:161-200) in one driver
    res = run_benchmark(_cfg(method=method, dtype=dtype))
    assert res.status == QAStatus.PASSED, res.to_dict()
    assert res.gbps > 0 and res.iterations == 3


def test_run_benchmark_xla_backend():
    res = run_benchmark(_cfg(backend="xla", method="MAX", dtype="float32"))
    assert res.passed


def test_waived_kernel():
    # kernels 0-5 -> WAIVED (reduction_kernel.cu:278-289 emptied cases)
    res = run_benchmark(_cfg(kernel=3))
    assert res.status == QAStatus.WAIVED


def test_two_pass_and_cpufinal():
    for kw in [dict(kernel=7), dict(kernel=7, cpu_final=True),
               dict(cpu_final=True)]:
        res = run_benchmark(_cfg(method="MIN", dtype="float32", n=100_000,
                                 threads=16, max_blocks=8, **kw))
        assert res.passed, res.to_dict()


def test_throughput_line_in_logs(tmp_path):
    app = tmp_path / "app.txt"
    master = tmp_path / "master.txt"
    logger = BenchLogger(str(app), str(master))
    run_benchmark(_cfg(), logger=logger)
    assert "Reduction, Throughput = " in master.read_text()


def test_cli_main_exit_codes(tmp_path, monkeypatch):
    monkeypatch.chdir(tmp_path)
    code = main(["--method=SUM", "--type=int", "--n=4096",
                 "--iterations=2", "--logfile", str(tmp_path / "r.txt")])
    assert code == 0


def test_run_benchmark_batch_defers_materialization(monkeypatch):
    """Batch runs must not materialize ANY device result until every timed
    loop has finished (the tunneled-TPU first-fetch sync penalty)."""
    import tpu_reductions.bench.driver as drv

    order = []
    real_time_fn = drv.time_fn

    def spy_time_fn(*a, **kw):
        order.append("timed")
        return real_time_fn(*a, **kw)

    real_finalize = drv._PendingResult.finalize

    def spy_finalize(self):
        order.append("finalized")
        return real_finalize(self)

    monkeypatch.setattr(drv, "time_fn", spy_time_fn)
    monkeypatch.setattr(drv._PendingResult, "finalize", spy_finalize)
    cfgs = [_cfg(), _cfg(method="MIN"), _cfg(method="MAX", backend="xla")]
    results = drv.run_benchmark_batch(cfgs, logger=BenchLogger(None, None))
    assert [r.status for r in results] == [QAStatus.PASSED] * 3
    assert order == ["timed"] * 3 + ["finalized"] * 3


def test_run_benchmark_batch_passes_through_waived():
    res, = __import__("tpu_reductions.bench.driver",
                      fromlist=["run_benchmark_batch"]).run_benchmark_batch(
        [_cfg(kernel=3)], logger=BenchLogger(None, None))
    assert res.status == QAStatus.WAIVED


def test_batch_warns_on_leaky_timing_order():
    """fetch/cpufinal configs materialize in-loop; batch flags them when
    they are not last (they would taint later configs on the tunnel)."""
    import io

    import tpu_reductions.bench.driver as drv

    buf = io.StringIO()
    log = BenchLogger(None, None, console=buf)
    drv.run_benchmark_batch([_cfg(timing="fetch"), _cfg()], logger=log)
    assert "WARNING" in buf.getvalue()
    buf2 = io.StringIO()
    drv.run_benchmark_batch([_cfg(), _cfg(timing="fetch")],
                            logger=BenchLogger(None, None, console=buf2))
    assert "WARNING" not in buf2.getvalue()
    # mixed case: a leaky LAST config must not mask the leaky FIRST one
    buf3 = io.StringIO()
    drv.run_benchmark_batch(
        [_cfg(timing="fetch"), _cfg(), _cfg(timing="fetch")],
        logger=BenchLogger(None, None, console=buf3))
    assert "WARNING" in buf3.getvalue()
    # --check materializes before later timed loops: leaky too
    buf4 = io.StringIO()
    drv.run_benchmark_batch([_cfg(check=True), _cfg()],
                            logger=BenchLogger(None, None, console=buf4))
    assert "WARNING" in buf4.getvalue()


def test_batch_on_result_hook():
    """on_result fires once per config, in order, after finalize."""
    import tpu_reductions.bench.driver as drv

    seen = []
    cfgs = [_cfg(), _cfg(method="MIN")]
    results = drv.run_benchmark_batch(
        cfgs, logger=BenchLogger(None, None),
        on_result=lambda cfg, res: seen.append((cfg.method, res.passed)))
    assert seen == [("SUM", True), ("MIN", True)]
    assert all(r.passed for r in results)


def test_kernel7_bf16_minmax_terminates():
    """bf16 MIN/MAX partials carry a 16-row sublane tile; the multi-pass
    loop's floor must track the partials' own tile or it never exits
    (regression: trace-time hang)."""
    from tpu_reductions.ops.pallas_reduce import pallas_reduce

    import jax.numpy as jnp
    for method in ("MIN", "MAX"):
        x = np.random.default_rng(0).integers(-100, 100, 1 << 16)
        got = pallas_reduce(jnp.asarray(x, jnp.bfloat16), method, kernel=7)
        want = (np.min if method == "MIN" else np.max)(
            np.asarray(x, np.float32).astype(jnp.bfloat16))
        assert float(got) == float(want)


def test_f64_dd_path_is_chained_on_tpu_backend(monkeypatch):
    """Driver wiring for the all-device f64 path: when the backend
    reports TPU, float64 routes through the dd pair kernels with the
    DEVICE pair-tree finish, is chain-supported, and produces a
    verified chained measurement (no fetch fallback). Simulated here by
    faking the backend name while pinning Pallas to interpret mode —
    the exact code path the real chip takes, minus Mosaic lowering."""
    import jax

    import tpu_reductions.ops.dd_reduce as dd
    import tpu_reductions.ops.pallas_reduce as pr
    from tpu_reductions.bench.driver import (_chain_supported,
                                             resolved_timing)

    # dd_reduce binds _interpret_default by name at import — patch BOTH
    # modules' bindings or the dd kernels try a real Mosaic lowering on
    # the CPU backend under the faked device name
    monkeypatch.setattr(pr, "_interpret_default", lambda: True)
    monkeypatch.setattr(dd, "_interpret_default", lambda: True)
    monkeypatch.setattr(jax, "default_backend", lambda: "tpu")

    cfg = ReduceConfig(method="SUM", dtype="float64", n=4096,
                       iterations=3, timing="chained", chain_reps=2,
                       backend="pallas", threads=32, log_file=None)
    assert _chain_supported(cfg)
    assert resolved_timing(cfg) == "chained"
    res = run_benchmark(cfg, logger=BenchLogger(None, None))
    assert res.timing == "chained"
    # chained slope CAN be noise-waived on a loaded host; correctness
    # must hold whenever the run wasn't waived
    if res.status != QAStatus.WAIVED:
        assert res.status == QAStatus.PASSED
        assert res.abs_diff < 1e-12
    # --cpufinal keeps the host-finish spelling and falls back to fetch
    cfg2 = ReduceConfig(method="MAX", dtype="float64", n=4096,
                        iterations=3, timing="chained", cpu_final=True,
                        backend="pallas", threads=32, log_file=None)
    assert not _chain_supported(cfg2)
    assert resolved_timing(cfg2) == "fetch"
    res2 = run_benchmark(cfg2, logger=BenchLogger(None, None))
    assert res2.status == QAStatus.PASSED and res2.timing == "fetch"


def test_benchresult_to_dict_serializes_nonfinite_as_null():
    """WAIVED/FAILED rows carry nan oracle fields (and a degenerate
    fetch-mode run reports inf gbps); their JSON form must be RFC-8259
    null, never the NaN/Infinity literals strict parsers reject
    (round-2 ADVICE 4)."""
    import json

    from tpu_reductions.bench.driver import BenchResult
    from tpu_reductions.utils.qa import QAStatus

    r = BenchResult("SUM", "int32", 64, "pallas", 6, float("inf"),
                    0.0, 0, QAStatus.WAIVED, float("nan"), float("nan"),
                    float("nan"))
    d = r.to_dict()
    assert d["gbps"] is None and d["device_result"] is None
    json.loads(json.dumps(d))  # strict round-trip
    ok = BenchResult("SUM", "int32", 64, "pallas", 6, 12.5, 1e-6, 4,
                     QAStatus.PASSED, 1.0, 1.0, 0.0)
    assert ok.to_dict()["gbps"] == 12.5


def test_noise_swamped_chained_slope_waives(monkeypatch):
    """The WAIVE-on-noise guard, pinned directly (driver.py: a
    non-positive chained slope must refuse to report a bandwidth):
    the CLI-shape tests stabilize their timing around this guard
    (tests/test_spot.py::stable_chained_timing), so the guard itself
    needs its own deterministic coverage."""
    import types

    from tpu_reductions.utils import timing as timing_mod

    monkeypatch.setattr(
        timing_mod, "time_chained",
        lambda *a, **kw: types.SimpleNamespace(average_s=-1e-6,
                                               median_s=-1e-6))
    cfg = ReduceConfig(method="SUM", dtype="int32", n=4096,
                       iterations=4, timing="chained", chain_reps=2,
                       backend="pallas", threads=256, log_file=None)
    res = run_benchmark(cfg, logger=BenchLogger(None, None))
    assert res.status == QAStatus.WAIVED
    assert "non-positive" in res.waived_reason
    assert res.gbps == 0.0


def test_chained_rows_carry_slope_samples_for_spread(monkeypatch):
    """Round-4 judge weak #7: the quoted chained median must travel
    with its per-rep spread — every chained BenchResult carries the raw
    slope samples, and they serialize RFC-8259-clean (non-finite
    members null)."""
    cfg = ReduceConfig(method="SUM", dtype="int32", n=4096,
                       iterations=4, timing="chained", chain_reps=3,
                       backend="pallas", threads=256, log_file=None)
    res = run_benchmark(cfg, logger=BenchLogger(None, None))
    assert isinstance(res.slope_samples_s, list)
    assert len(res.slope_samples_s) == 3
    import json

    from tpu_reductions.bench.driver import BenchResult
    r2 = BenchResult("SUM", "int32", 64, "pallas", 6, 1.0, 1e-4, 4,
                     QAStatus.PASSED, 1.0, 1.0, 0.0,
                     slope_samples_s=[1e-4, float("nan")])
    d2 = r2.to_dict()
    assert d2["slope_samples_s"] == [1e-4, None]
    json.loads(json.dumps(d2))   # strict round-trip

    # fetch-mode rows must NOT mislabel launch times as slopes
    cfg_f = ReduceConfig(method="SUM", dtype="int32", n=4096,
                         iterations=4, timing="fetch",
                         backend="pallas", threads=256, log_file=None)
    res_f = run_benchmark(cfg_f, logger=BenchLogger(None, None))
    assert res_f.slope_samples_s is None


# ---------------------------------------------------------------------------
# Bugfix sweep (ISSUE 6 satellite): crash_result() and the
# _PendingResult.finalize() error path were only exercised implicitly
# through batch/race flows — pin their contracts directly.
# ---------------------------------------------------------------------------


def test_crash_result_row_contract():
    """crash_result: a raised config becomes a FAILED row that keeps
    the batch alive — identity preserved, reason truncated, RFC-8259
    serializable, and never mistaken for a measurement."""
    import json

    from tpu_reductions.bench.driver import crash_result

    cfg = _cfg(method="MIN", dtype="float32", n=1 << 20, kernel=9,
               timing="chained")
    err = ValueError("Mosaic lowering gap: " + "x" * 400)
    res = crash_result(cfg, err)
    assert res.status == QAStatus.FAILED and not res.passed
    assert (res.method, res.dtype, res.n, res.kernel) \
        == ("MIN", "float32", 1 << 20, 9)
    assert res.gbps == 0.0 and res.avg_s == 0.0 and res.iterations == 0
    assert res.timing == "chained"
    assert res.waived_reason.startswith("ValueError: Mosaic lowering")
    assert len(res.waived_reason) == 200          # bounded reason
    d = res.to_dict()
    # nan oracle fields serialize as null — strict parsers must accept
    assert d["device_result"] is None and d["oracle_result"] is None
    json.loads(json.dumps(d))
    assert d["status"] == "FAILED"


def test_crash_result_logs_the_config_identity():
    from tpu_reductions.bench.driver import crash_result

    lines = []

    class _Log:
        def log(self, msg):
            lines.append(msg)

    cfg = _cfg(kernel=7, threads=384)
    crash_result(cfg, RuntimeError("tunnel reset"), _Log())
    assert any("kernel=7" in ln and "threads=384" in ln
               and "tunnel reset" in ln for ln in lines)


def test_batch_contains_finalize_error_to_one_config(monkeypatch):
    """A _PendingResult whose finalize() raises (the materialization/
    verification half dying — e.g. the relay resetting between the
    timed loop and the fetch) must become a FAILED row via
    crash_result, and must NOT take the rest of the batch with it."""
    from tpu_reductions.bench import driver

    real_run = driver.run_benchmark
    boom_cfg_n = 2048

    class _Boom(driver._PendingResult):
        def finalize(self):
            raise RuntimeError("relay reset during materialization")

    def fake_run(cfg, logger=None, defer=False):
        assert defer
        if cfg.n == boom_cfg_n:
            return _Boom(cfg, "pallas", 0.0, 0.0, None, None, logger)
        return real_run(cfg, logger=logger, defer=defer)

    monkeypatch.setattr(driver, "run_benchmark", fake_run)
    cfgs = [_cfg(n=boom_cfg_n), _cfg(n=4096)]
    seen = []
    results = driver.run_benchmark_batch(
        cfgs, logger=BenchLogger(None, None),
        on_result=lambda cfg, res: seen.append((cfg.n, res.status)))
    assert results[0].status == QAStatus.FAILED
    assert "relay reset during materialization" in results[0].waived_reason
    assert results[1].status == QAStatus.PASSED    # batch survived
    # the on_result hook saw BOTH rows, crash row included — the seam
    # sweep's per-cell persistence relies on
    assert seen == [(2048, QAStatus.FAILED), (4096, QAStatus.PASSED)]


def test_batch_contains_dispatch_error_to_one_config(monkeypatch):
    """The dispatch half of the same containment: run_benchmark itself
    raising inside the batch loop yields a crash row for that config
    only (the per-call fail-fast of cutil scoped to the config)."""
    from tpu_reductions.bench import driver

    real_run = driver.run_benchmark

    def fake_run(cfg, logger=None, defer=False):
        if cfg.method == "MIN":
            raise RuntimeError("compile exploded")
        return real_run(cfg, logger=logger, defer=defer)

    monkeypatch.setattr(driver, "run_benchmark", fake_run)
    results = driver.run_benchmark_batch(
        [_cfg(method="MIN"), _cfg(method="MAX")],
        logger=BenchLogger(None, None))
    assert results[0].status == QAStatus.FAILED
    assert "compile exploded" in results[0].waived_reason
    assert results[1].status == QAStatus.PASSED
