"""Reduction-family properties (ISSUE 20; docs/FAMILY.md): scan
chunk-carry vs one-shot bit-identity, the MXU matmul trick vs the XLA
cumsum, segmented reduce against per-segment numpy (ragged + empty
segments), arg-reduce lowest-index ties on device AND oracle, the
registry/oracle round-trips, the serving wire end-to-end, the spot
instrument's grid + report fold, and the `family.cell` exit-3
mid-artifact resume (docs/RESILIENCE.md fault-point table)."""

import json
import os
import subprocess
import sys
from pathlib import Path

import numpy as np
import pytest

from tpu_reductions.config import FAMILY_METHODS, SERVED_METHODS
from tpu_reductions.ops import family as fam
from tpu_reductions.ops import oracle as oracle_mod
from tpu_reductions.ops.registry import get_op, tolerance
from tpu_reductions.utils.rng import host_data

REPO = Path(__file__).resolve().parent.parent


# ------------------------------------------------------------------ scan

def test_scan_int32_chunk_carry_is_bit_identical_to_one_shot():
    """Modular addition is associative: the StreamScanner's chunk-carry
    recurrence must reproduce the one-shot cumsum EXACTLY for int32,
    including across the wrap."""
    n = 1 << 14
    x = host_data(n, "int32", rank=0, seed=3)
    # force the wrap into play: large magnitudes on top of the byte fill
    x = (x.astype(np.int64) * 0x0FFFFFFF).astype(np.int32)
    sc = fam.StreamScanner("int32", n, chunk_bytes=4096)
    got = sc.scan(x)
    assert sc.plan.num_chunks > 1   # the chunk boundary is exercised
    want = fam.host_scan(x)
    assert np.array_equal(got, want)
    # the carry is the running total — the next chunk's additive offset
    assert int(sc.carry) == int(want[-1])


def test_scan_float_chunk_carry_within_sum_tolerance():
    n = 1 << 14
    x = host_data(n, "float32", rank=0, seed=1)
    sc = fam.StreamScanner("float32", n, chunk_bytes=4096)
    got = sc.scan(x)
    want = fam.host_scan(x)
    assert float(np.abs(got.astype(np.float64) - want).max()) \
        <= tolerance("SUM", "float32", n)


def test_mxu_scan_matches_cumsum_baseline():
    """The paper's trick (x @ upper-triangular ones per 128-block plus
    a carry level, arXiv:1811.09736) against jnp.cumsum — including a
    non-multiple-of-128 length, which exercises the pad/slice edges."""
    import jax

    for n in (1 << 12, (1 << 12) + 37):
        x = host_data(n, "float32", rank=0, seed=2)
        zero = np.float32(0)
        a = np.asarray(jax.device_get(
            fam.scan_fn("mxu-scan", "float32")(x, zero)))
        b = np.asarray(jax.device_get(
            fam.scan_fn("xla-cumsum", "float32")(x, zero)))
        want = fam.host_scan(x)
        for got in (a, b):
            assert got.shape == (n,)
            assert float(np.abs(got.astype(np.float64) - want).max()) \
                <= tolerance("SUM", "float32", n)


def test_scan_impls_gates_mxu_to_floats():
    assert fam.scan_impls("float32") == ("xla-cumsum", "mxu-scan")
    assert fam.scan_impls("bfloat16") == ("xla-cumsum", "mxu-scan")
    assert fam.scan_impls("int32") == ("xla-cumsum",)
    with pytest.raises(ValueError, match="float-only"):
        fam.scan_fn("mxu-scan", "int32")


# ------------------------------------------------------- segmented reduce

@pytest.mark.parametrize("method", ["SEGSUM", "SEGMIN", "SEGMAX"])
@pytest.mark.parametrize("dtype", ["int32", "float32"])
def test_segmented_reduce_matches_per_segment_numpy(method, dtype):
    """Device segment reduce vs a literal per-segment numpy loop over
    ragged random offsets — duplicate cuts guarantee EMPTY segments,
    which must land the identity on both sides."""
    import jax

    n, segs = 256, 64   # 63 cuts in [0,256]: duplicate cuts (= empty
    #                     segments) occur with near-certainty
    x = host_data(n, dtype, rank=0, seed=5)
    offsets = fam.random_offsets(n, segs, seed=7)
    assert offsets[0] == 0 and offsets[-1] == n
    widths = np.diff(offsets)
    assert (widths == 0).any()      # ragged by construction
    ids = fam.segment_ids_from_offsets(offsets)
    got = np.asarray(jax.device_get(
        fam.segment_reduce_fn(method, segs)(x, ids))).astype(np.float64)
    want = fam.host_segment_reduce(x, offsets, method)
    assert got.shape == want.shape == (segs,)
    for s in range(segs):
        seg = x[offsets[s]:offsets[s + 1]]
        if seg.size == 0:
            # identity agreement: device fill == host fill (+-inf for
            # float MIN/MAX, iinfo extremes for int)
            assert got[s] == want[s] or (np.isinf(got[s])
                                         and got[s] == want[s])
            continue
        ref = {"SEGSUM": seg.astype(np.float64).sum(),
               "SEGMIN": float(seg.min()),
               "SEGMAX": float(seg.max())}[method]
        tol = tolerance("SUM", dtype, int(seg.size)) \
            if method == "SEGSUM" and dtype != "int32" else 0.0
        assert abs(want[s] - ref) <= tol
        assert abs(got[s] - ref) <= tol


def test_segment_ids_round_trip_offsets():
    offsets = np.array([0, 3, 3, 7, 10], dtype=np.int64)
    ids = fam.segment_ids_from_offsets(offsets)
    assert ids.tolist() == [0, 0, 0, 2, 2, 2, 2, 3, 3, 3]


# ------------------------------------------------------------- arg reduce

@pytest.mark.parametrize("method", ["ARGMIN", "ARGMAX"])
@pytest.mark.parametrize("dtype", ["int32", "float32"])
def test_arg_reduce_exact_with_lowest_index_tie(method, dtype):
    import jax

    n = 1 << 12
    x = host_data(n, dtype, rank=0, seed=11)
    # plant the extreme value at three positions: the LOWEST index wins
    lo, mid, hi = 100, n // 2, n - 7
    extreme = (np.dtype(dtype).type(300)
               if method == "ARGMAX" else np.dtype(dtype).type(-5))
    x = x.copy()
    x[lo] = x[mid] = x[hi] = extreme
    got = int(jax.device_get(fam.arg_reduce_fn(method, dtype)(x)))
    assert got == lo
    assert int(fam.host_arg_reduce(x, method)) == lo
    # numpy's first-occurrence rule is the same contract
    ref = int(np.argmax(x) if method == "ARGMAX" else np.argmin(x))
    assert got == ref


def test_arg_reduce_rows_batches_independently():
    import jax

    k, n = 4, 512
    rows = np.stack([host_data(n, "float32", rank=r, seed=13)
                     for r in range(k)])
    got = np.asarray(jax.device_get(
        fam.arg_reduce_rows_fn("ARGMIN", "float32")(rows)))
    want = rows.argmin(axis=1)
    assert np.array_equal(got, want)


# ------------------------------------------- registry / oracle round-trip

def test_family_methods_registered_and_served():
    assert FAMILY_METHODS == ("SCAN", "SEGSUM", "SEGMIN", "SEGMAX",
                              "ARGMIN", "ARGMAX")
    for m in FAMILY_METHODS:
        assert m in SERVED_METHODS
        op = get_op(m)
        assert op is not None
        assert fam.is_family_method(m)
    assert not fam.is_family_method("SUM")


def test_family_surfaces_vocabulary():
    assert fam.family_surface("SCAN", "mxu-scan") == "mxu-scan"
    assert fam.family_surface("SCAN") == "xla-cumsum"
    assert fam.family_surface("SEGSUM") == "seg/segsum"
    assert fam.family_surface("ARGMAX") == "argk/argmax"
    with pytest.raises(ValueError):
        fam.family_surface("SUM")


def test_family_tolerances_follow_registry_classes():
    n = 1 << 20
    assert tolerance("SCAN", "float32", n) == tolerance("SUM", "float32",
                                                        n)
    for m in ("SEGMIN", "SEGMAX", "ARGMIN", "ARGMAX"):
        assert tolerance(m, "float32", n) == 0.0


def test_incremental_oracle_scan_and_arg_resume_round_trip():
    n = 1 << 12
    x = host_data(n, "int32", rank=0, seed=17)
    o = oracle_mod.IncrementalOracle("SCAN", "int32")
    o.update(x[: n // 2])
    o = oracle_mod.IncrementalOracle.from_state(
        json.loads(json.dumps(o.state())))   # the stream resume path
    o.update(x[n // 2:])
    assert int(o.value()) == int(fam.host_scan(x)[-1])

    a = oracle_mod.IncrementalOracle("ARGMIN", "int32")
    y = x.copy()
    y[10] = y[3000] = -9    # tie across the chunk boundary: index 10 wins
    a.update(y[:2048])
    a = oracle_mod.IncrementalOracle.from_state(a.state())
    a.update(y[2048:])
    assert int(a.value()) == 10


# ------------------------------------------------------------ serving wire

def _serve_payload(n, dtype, seed):
    """The executor's own payload convention (serve/executor.py:
    native MT19937 fill when the C oracle built, utils.rng fallback)."""
    x = oracle_mod.native_fill(n, dtype, rank=0, seed=seed)
    return x if x is not None else host_data(n, dtype, rank=0,
                                             seed=seed)


def test_serve_engine_resolves_family_requests_end_to_end():
    """The ISSUE 20 serving acceptance, in-process: SCAN / SEGSUM /
    ARGMAX requests through the real coalescing engine resolve `ok`
    with results the host oracle agrees with."""
    from tpu_reductions.serve.engine import ServeEngine
    from tpu_reductions.serve.request import ReduceRequest

    eng = ServeEngine(coalesce_window_s=0.0).start()
    try:
        pends = [eng.submit(ReduceRequest(method=m, dtype=d, n=4096,
                                          seed=s))
                 for s, (m, d) in enumerate([("SCAN", "float32"),
                                             ("SEGSUM", "int32"),
                                             ("ARGMAX", "float32")])]
        resps = [p.result(timeout=60.0) for p in pends]
    finally:
        eng.stop()
    assert [r.status for r in resps] == ["ok", "ok", "ok"]
    # SCAN's scalar result is the last prefix == the full SUM digest
    x = _serve_payload(4096, "float32", 0)
    assert abs(resps[0].result - float(x.astype(np.float64).sum())) \
        <= tolerance("SUM", "float32", 4096)
    # ARGMAX returns the (exact) index as the scalar
    x2 = _serve_payload(4096, "float32", 2)
    assert int(resps[2].result) == int(np.argmax(x2))


def test_serve_executor_guards_family_stream_and_sharded():
    from tpu_reductions.serve.executor import BatchExecutor

    ex = BatchExecutor()
    with pytest.raises(ValueError, match="no streaming path"):
        ex.run_stream("SEGSUM", "int32", 1 << 12, 0)
    with pytest.raises(ValueError, match="no device-parallel path"):
        ex.run_sharded("ARGMAX", "float32", 1 << 12, 0)


def test_serve_stream_scan_chunk_carries():
    from tpu_reductions.serve.executor import BatchExecutor

    res = BatchExecutor().run_stream("SCAN", "int32", 1 << 12, 0,
                                     chunk_bytes=4096)
    assert res["ok"] is True and res["chunks"] > 1
    x = _serve_payload(1 << 12, "int32", 0)
    assert int(res["result"]) == int(fam.host_scan(x)[-1])


# --------------------------------------------------- the spot instrument

def test_family_spot_grid_covers_every_method_and_serving_row():
    from tpu_reductions.bench.family_spot import (SERVE_CELLS,
                                                  family_cells)

    cells = family_cells()
    methods = {m for kind, m, _, _ in cells if kind == "cell"}
    assert methods == set(FAMILY_METHODS)
    scan_impls = [(d, i) for kind, m, d, i in cells
                  if kind == "cell" and m == "SCAN"]
    assert ("float32", "mxu-scan") in scan_impls     # the race happens
    assert ("int32", "mxu-scan") not in scan_impls   # float-only guard
    assert [(m, d) for kind, m, d, _ in cells if kind == "serve"] \
        == list(SERVE_CELLS)
    assert len(cells) == len(set(cells))


@pytest.mark.parametrize("method,dtype,impl", [
    ("SCAN", "float32", "mxu-scan"),
    ("SEGSUM", "int32", "seg"),
    ("ARGMAX", "float32", "argk"),
])
def test_family_spot_cell_verifies_and_times(method, dtype, impl,
                                             stable_chained_timing):
    from tpu_reductions.bench.family_spot import measure_cell

    row = measure_cell(method, dtype, impl, n=1 << 12, segments=16,
                       seed=0, reps=1)
    assert row["status"] == "PASSED"
    assert row["gbps"] > 0
    # the cost oracle consumes exactly these spellings (exec/cost.py
    # scan_rates): a key rename here silently unprices the scan axis
    assert {"method", "dtype", "impl", "gbps", "status"} <= set(row)


def test_family_spot_markdown_folds_cells_and_serve_rows():
    from tpu_reductions.bench.family_spot import family_spot_markdown

    assert family_spot_markdown({"rows": []}) == ""
    md = family_spot_markdown({"n": 4096, "rows": [
        {"kind": "cell", "method": "SCAN", "dtype": "float32",
         "impl": "mxu-scan", "n": 4096, "gbps": 1.25, "max_err": 0.0,
         "status": "PASSED"},
        {"kind": "serve", "method": "SEGSUM", "dtype": "int32",
         "n": 512, "requests": 3, "ok_count": 3, "status": "PASSED"},
    ]})
    assert "| SCAN | float32 | mxu-scan | 1.250 |" in md
    assert "| SEGSUM | int32 | 512 | 3/3 | PASSED |" in md
    assert "pick_scan" in md


# ---------------------------------------------------- chaos: exit-3 resume

def _spot_cmd(out):
    return [sys.executable, "-m", "tpu_reductions.bench.family_spot",
            "--platform=cpu", "--n=16384", "--serve-n=2048",
            "--segments=16", "--reps=1", f"--out={out}"]


def _spot_env(faults=None):
    env = {**os.environ}
    env.pop("TPU_REDUCTIONS_LEDGER", None)
    env["XLA_FLAGS"] = (env.get("XLA_FLAGS", "")
                        + " --xla_force_host_platform_device_count=8")
    if faults is None:
        env.pop("TPU_REDUCTIONS_FAULTS", None)
    else:
        env["TPU_REDUCTIONS_FAULTS"] = json.dumps(faults)
    return env


def test_chaos_family_spot_exit3_midgrid_resumes_rows(tmp_path):
    """The `family.cell` fault point fires before each cell's payload
    exists; a scripted exit-3 after 3 cells is the relay death between
    family cells. The interrupted artifact must hold exactly the
    finished rows (`complete: false`), and the re-invocation must
    resume them byte-identically (docs/RESILIENCE.md; bench/resume)."""
    out = tmp_path / "family_spot.json"
    p = subprocess.run(
        _spot_cmd(out), cwd=str(REPO), capture_output=True, text=True,
        timeout=300,
        env=_spot_env(faults={"family.cell": {"after": 3,
                                              "action": "exit",
                                              "code": 3}}))
    assert p.returncode == 3, p.stderr
    interrupted = json.loads(out.read_text())
    assert interrupted["complete"] is False
    assert len(interrupted["rows"]) == 3
    assert all(r["status"] == "PASSED" for r in interrupted["rows"])

    p2 = subprocess.run(_spot_cmd(out), cwd=str(REPO),
                        capture_output=True, text=True, timeout=600,
                        env=_spot_env())
    assert p2.returncode == 0, p2.stderr
    resumed = json.loads(out.read_text())
    assert resumed["complete"] is True
    assert len(resumed["rows"]) == 16   # 13 cells + 3 serving rows
    # banked rows reused byte-identically, never re-measured
    assert resumed["rows"][:3] == interrupted["rows"]
    assert all(r["status"] == "PASSED" for r in resumed["rows"])
