"""L5 autotuner tests: the tile-geometry race that replaces the
reference's hand-set --threads/--maxblocks knobs (reduction.cpp:666-668;
SURVEY.md §7 step 3). Runs on the virtual CPU platform via conftest."""

import json

from tpu_reductions.bench.autotune import autotune, candidate_configs, main
from tpu_reductions.config import ReduceConfig


def _base(n=1 << 14):
    return ReduceConfig(method="SUM", dtype="int32", n=n, iterations=3,
                        warmup=1, log_file=None)


def test_candidate_grid_shapes():
    cfgs = candidate_configs(_base())
    assert all(c.backend == "pallas" for c in cfgs)
    kernels = {c.kernel for c in cfgs}
    assert kernels == {6, 7, 8, 9, 10}
    # two-pass candidates vary max_blocks; single-pass pin it to 64
    assert {c.max_blocks for c in cfgs if c.kernel == 7} == {64, 256}
    assert {c.max_blocks for c in cfgs if c.kernel != 7} == {64}


def test_autotune_ranks_verified_first():
    grid = ((6, 256, 64), (8, 256, 64), (7, 256, 64))
    pairs = autotune(_base(), grid=grid)
    assert len(pairs) == 3
    # every candidate verifies on the interpret path, so ordering is by
    # throughput alone — descending
    assert all(res.passed for _, res in pairs)
    speeds = [res.gbps for _, res in pairs]
    assert speeds == sorted(speeds, reverse=True)


def test_autotune_cli_writes_json(tmp_path, capsys):
    out = tmp_path / "tune.json"
    rc = main(["--method=SUM", "--type=int", "--n=16384", "--iterations=2",
               f"--out={out}"])
    assert rc == 0
    data = json.loads(out.read_text())
    assert data["dtype"] == "int32" and data["n"] == 16384
    assert data["best"] is not None
    assert data["best"]["status"] == "PASSED"
    assert len(data["ranked"]) == len(candidate_configs(_base()))
    assert "best:" in capsys.readouterr().out


def test_fine_grid_is_valid_and_distinct():
    """--grid=fine: every candidate is a valid (kernel, threads,
    maxblocks) triple over the live kernels, with no duplicates — the
    second-pass race around the committed round-2 winners."""
    from tpu_reductions.bench.autotune import FINE_GRID, GRIDS
    from tpu_reductions.config import LIVE_KERNELS

    assert GRIDS["fine"] is FINE_GRID
    assert len(set(FINE_GRID)) == len(FINE_GRID)
    for k, t, mb in FINE_GRID:
        assert k in LIVE_KERNELS and t > 0 and mb > 0


def test_hbm_grid_and_comparator_row():
    """The 'hbm' preset exists for the HBM-regime race
    (docs/PERF_NOTES.md next-window hypotheses) and --comparator
    appends exactly one XLA row so the race records the baseline the
    Pallas winner must beat in the same discipline."""
    from tpu_reductions.bench.autotune import (GRIDS, HBM_GRID,
                                               candidate_configs)
    from tpu_reductions.config import ReduceConfig

    assert GRIDS["hbm"] is HBM_GRID
    base = ReduceConfig(method="SUM", dtype="int32", n=1 << 14,
                        log_file=None)
    cfgs = candidate_configs(base, HBM_GRID, comparator=True)
    assert len(cfgs) == len(HBM_GRID) + 1
    assert [c.backend for c in cfgs].count("xla") == 1
    # the comparator leads the race: a budget-cut race keeps its
    # yardstick row (round-4 flapping-relay discipline)
    assert cfgs[0].backend == "xla"
    assert all(c.backend == "pallas" for c in cfgs[1:])
    # and the kernel-10 depth race leads the Pallas candidates
    assert [c.kernel for c in cfgs[1:4]] == [10, 10, 10]
    assert [c.stream_buffers for c in cfgs[1:4]] == [4, 8, 2]


def test_autotune_cli_comparator_races_xla(capsys, tmp_path):
    """End-to-end: a tiny --grid=hbm --comparator race on CPU ranks the
    XLA row alongside the Pallas candidates and records backends in the
    JSON output."""
    import json

    from tpu_reductions.bench import autotune as at

    out = tmp_path / "t.json"
    rc = at.main(["--method=SUM", "--type=int", "--n=8192",
                  "--iterations=3", "--timing=fetch", "--grid=hbm",
                  "--comparator", "--platform=cpu", f"--out={out}"])
    assert rc == 0
    data = json.loads(out.read_text())
    backends = {r["backend"] for r in data["ranked"]}
    assert backends == {"pallas", "xla"}
    assert sum(r["backend"] == "xla" for r in data["ranked"]) == 1
    # the comparator is a fixed baseline, never the recommendation:
    # best must be a tunable (pallas) geometry even when XLA ranks
    # first (on CPU the XLA row routinely wins the race)
    assert data["best"]["backend"] == "pallas"
    assert data["best"]["status"] == "PASSED"


def test_chained_race_persists_per_candidate(tmp_path):
    """In chained mode candidates run one at a time and on_result fires
    after EACH (mid-race persistence: a race that dies at candidate k
    keeps candidates 1..k-1). The --out file is written incrementally
    with complete=false, then finalized with complete=true and best."""
    import json

    from tpu_reductions.bench import autotune as at
    from tpu_reductions.config import KERNEL_SINGLE_PASS, ReduceConfig

    grid = ((KERNEL_SINGLE_PASS, 16, 8), (KERNEL_SINGLE_PASS, 32, 8))
    base = ReduceConfig(method="SUM", dtype="int32", n=4096,
                        iterations=4, timing="chained", chain_reps=2,
                        log_file=None)
    seen = []
    snapshots = []
    out = tmp_path / "race.json"

    from tpu_reductions.bench.resume import Checkpoint
    ck = Checkpoint(str(out), {"method": "SUM", "dtype": "int32",
                               "n": 4096},
                    rows_key="ranked", key_fn=at._row_key)

    def spy(cfg, res):
        seen.append((cfg.kernel, cfg.threads, res.status.name))
        # main()'s persist: the file state after each candidate is
        # what a mid-race death would leave behind
        ck.add(at._row(cfg, res), extra={"best": None})
        snapshots.append(json.loads(out.read_text()))

    pairs = at.autotune(base, grid=grid, on_result=spy)
    assert len(seen) == 2 == len(pairs)
    assert [s[:2] for s in seen] == [(KERNEL_SINGLE_PASS, 16),
                                     (KERNEL_SINGLE_PASS, 32)]
    # every mid-race snapshot was valid, complete=false JSON
    assert all(s["complete"] is False for s in snapshots)


def test_cli_out_file_marks_completion(tmp_path):
    """End-to-end through main(): the final --out file carries
    complete=true and a pallas best; the schema includes the
    incremental-persistence fields."""
    import json

    from tpu_reductions.bench import autotune as at

    out = tmp_path / "t.json"
    rc = at.main(["--method=SUM", "--type=int", "--n=4096",
                  "--iterations=4", "--timing=chained", "--chainreps=2",
                  "--grid=fine", "--platform=cpu", f"--out={out}"])
    data = json.loads(out.read_text())
    assert data["complete"] is True
    if rc == 0:
        assert data["best"]["backend"] == "pallas"
    assert len(data["ranked"]) == len(at.FINE_GRID)


def test_chained_race_survives_a_crashing_candidate(monkeypatch):
    """A candidate whose kernel cannot even compile (a Mosaic lowering
    gap the interpret path does not have) must record FAILED and leave
    the rest of the race intact - a live chip session cannot afford a
    process-killing candidate."""
    from tpu_reductions.bench import autotune as at
    from tpu_reductions.bench import driver as drv
    from tpu_reductions.config import KERNEL_SINGLE_PASS, ReduceConfig

    real = drv.run_benchmark

    def sabotaged(cfg, **kw):
        if cfg.threads == 16:
            raise RuntimeError("synthetic lowering failure")
        return real(cfg, **kw)

    monkeypatch.setattr(drv, "run_benchmark", sabotaged)
    base = ReduceConfig(method="SUM", dtype="int32", n=4096,
                        iterations=4, timing="chained", chain_reps=2,
                        log_file=None)
    grid = ((KERNEL_SINGLE_PASS, 16, 8), (KERNEL_SINGLE_PASS, 32, 8))
    pairs = at.autotune(base, grid=grid)
    assert len(pairs) == 2
    by_threads = {cfg.threads: res for cfg, res in pairs}
    assert by_threads[16].status.name == "FAILED"
    assert "synthetic lowering failure" in by_threads[16].waived_reason
    # the healthy candidate may noise-WAIVE on a loaded host (tiny
    # chained payload); what matters here is the crash never spread
    assert by_threads[32].status.name in ("PASSED", "WAIVED")


def test_hbm_grid_races_stream_depth():
    """The hbm grid's kernel-10 rows race the DMA pipeline depth (2 =
    Mosaic-equivalent, 4 = default, 8 = deep) — the knob the streaming
    kernel exists for (round-2 VERDICT weak #7: maxblocks is
    structurally dead for single-pass kernels; depth is not)."""
    from tpu_reductions.bench.autotune import HBM_GRID, candidate_configs
    from tpu_reductions.config import KERNEL_STREAM, ReduceConfig

    depths = {g[3] for g in HBM_GRID if g[0] == KERNEL_STREAM}
    assert depths == {2, 4, 8}
    base = ReduceConfig(method="SUM", dtype="int32", n=1 << 14,
                        log_file=None)
    cfgs = candidate_configs(base, HBM_GRID)
    k10 = [c for c in cfgs if c.kernel == KERNEL_STREAM]
    assert {c.stream_buffers for c in k10} == {2, 4, 8}
    # 3-tuple rows inherit base's depth untouched
    assert all(c.stream_buffers == base.stream_buffers
               for c in cfgs if c.kernel != KERNEL_STREAM)


def test_mxu_grid_registered_and_races_float(tmp_path):
    """--grid=mxu: the kernel-9 race preset (float SUM) ranks the MXU
    kernel against the established VPU winners; rows record the k10
    depth so the artifact is self-describing."""
    import json

    from tpu_reductions.bench import autotune as at
    from tpu_reductions.config import KERNEL_MXU, KERNEL_STREAM

    assert at.GRIDS["mxu"] is at.MXU_GRID
    assert sum(g[0] == KERNEL_MXU for g in at.MXU_GRID) == 3
    out = tmp_path / "mxu.json"
    rc = at.main(["--method=SUM", "--type=float", "--n=8192",
                  "--iterations=3", "--timing=fetch", "--grid=mxu",
                  "--comparator", "--platform=cpu", f"--out={out}"])
    assert rc == 0
    data = json.loads(out.read_text())
    kernels = {r["kernel"] for r in data["ranked"]}
    assert KERNEL_MXU in kernels and None in kernels  # + comparator row
    k10_rows = [r for r in data["ranked"]
                if r["kernel"] == KERNEL_STREAM]
    assert all(r["stream_buffers"] == 4 for r in k10_rows)
