"""L5 autotuner tests: the tile-geometry race that replaces the
reference's hand-set --threads/--maxblocks knobs (reduction.cpp:666-668;
SURVEY.md §7 step 3). Runs on the virtual CPU platform via conftest."""

import json

from tpu_reductions.bench.autotune import autotune, candidate_configs, main
from tpu_reductions.config import ReduceConfig


def _base(n=1 << 14):
    return ReduceConfig(method="SUM", dtype="int32", n=n, iterations=3,
                        warmup=1, log_file=None)


def test_candidate_grid_shapes():
    cfgs = candidate_configs(_base())
    assert all(c.backend == "pallas" for c in cfgs)
    kernels = {c.kernel for c in cfgs}
    assert kernels == {6, 7, 8, 9}
    # two-pass candidates vary max_blocks; single-pass pin it to 64
    assert {c.max_blocks for c in cfgs if c.kernel == 7} == {64, 256}
    assert {c.max_blocks for c in cfgs if c.kernel != 7} == {64}


def test_autotune_ranks_verified_first():
    grid = ((6, 256, 64), (8, 256, 64), (7, 256, 64))
    pairs = autotune(_base(), grid=grid)
    assert len(pairs) == 3
    # every candidate verifies on the interpret path, so ordering is by
    # throughput alone — descending
    assert all(res.passed for _, res in pairs)
    speeds = [res.gbps for _, res in pairs]
    assert speeds == sorted(speeds, reverse=True)


def test_autotune_cli_writes_json(tmp_path, capsys):
    out = tmp_path / "tune.json"
    rc = main(["--method=SUM", "--type=int", "--n=16384", "--iterations=2",
               f"--out={out}"])
    assert rc == 0
    data = json.loads(out.read_text())
    assert data["dtype"] == "int32" and data["n"] == 16384
    assert data["best"] is not None
    assert data["best"]["status"] == "PASSED"
    assert len(data["ranked"]) == len(candidate_configs(_base()))
    assert "best:" in capsys.readouterr().out


def test_fine_grid_is_valid_and_distinct():
    """--grid=fine: every candidate is a valid (kernel, threads,
    maxblocks) triple over the live kernels, with no duplicates — the
    second-pass race around the committed round-2 winners."""
    from tpu_reductions.bench.autotune import FINE_GRID, GRIDS
    from tpu_reductions.config import LIVE_KERNELS

    assert GRIDS["fine"] is FINE_GRID
    assert len(set(FINE_GRID)) == len(FINE_GRID)
    for k, t, mb in FINE_GRID:
        assert k in LIVE_KERNELS and t > 0 and mb > 0
