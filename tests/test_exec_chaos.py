"""Chaos coverage for the execution core's one deterministic seam
(ISSUE 19; docs/RESILIENCE.md `exec.launch`): a scripted death between
the exec.plan record and the launch — the relay dying mid-plan — kills
a REAL rewired entry point (bench/spot), the re-invocation resumes its
persisted rows through exec/core, and the ledger join across BOTH runs
proves zero duplicate launches: the interrupted plan re-plans, the
already-persisted row never re-enters the core at all."""

import json
import os
import subprocess
import sys
from pathlib import Path

from tpu_reductions.faults import inject
from tpu_reductions.faults.inject import InjectedFault, fault_point

REPO = Path(__file__).resolve().parent.parent
SPOT_ARGS = ["--platform=cpu", "--type=int", "--methods=SUM,MIN,MAX",
             "--n=16384", "--iterations=8", "--chainreps=2"]


def _env(*, faults=None, ledger=None):
    env = {**os.environ}
    for k in ("TPU_REDUCTIONS_FAULTS", "TPU_REDUCTIONS_LEDGER",
              "TPU_REDUCTIONS_CHAOS_ARM"):
        env.pop(k, None)
    if faults is not None:
        env["TPU_REDUCTIONS_FAULTS"] = json.dumps(faults)
    if ledger is not None:
        env["TPU_REDUCTIONS_LEDGER"] = str(ledger)
    return env


def _spot(out, env, methods=None):
    args = list(SPOT_ARGS)
    if methods is not None:
        args = [a for a in args if not a.startswith("--methods=")]
        args.append(f"--methods={methods}")
    return subprocess.run(
        [sys.executable, "-m", "tpu_reductions.bench.spot",
         *args, f"--out={out}"],
        env=env, cwd=str(REPO), capture_output=True, text=True,
        timeout=300)


def _events(led: Path):
    return [json.loads(line) for line in
            led.read_text().splitlines() if line.strip()]


def test_exec_launch_fault_point_fires_in_core(monkeypatch):
    """The seam is wired: a scripted raise at exec.launch surfaces
    from run(plan) AFTER the exec.plan record, before any builder
    work (the builder never runs)."""
    import pytest

    from tpu_reductions.exec import core as exec_core
    from tpu_reductions.exec.plan import launch_plan
    monkeypatch.setenv("TPU_REDUCTIONS_FAULTS",
                       json.dumps({"exec.launch": {"action": "raise"}}))
    inject.reset()
    ran = {"builder": False}

    def builder(ctx):
        ran["builder"] = True
        return 1

    with pytest.raises(InjectedFault):
        exec_core.run(launch_plan("unit/fault", "bench", builder))
    assert ran["builder"] is False
    monkeypatch.delenv("TPU_REDUCTIONS_FAULTS")
    inject.reset()
    assert fault_point("exec.launch") is None


def test_death_mid_plan_resumes_with_zero_duplicate_launches(tmp_path):
    """The full pipeline. A spot method is a TREE of plans — the
    spot-level bench plan nests the chained trips' own chain plans
    (surface k6) — so the death point is calibrated, not guessed: a
    clean SUM-only run counts the exec.plan records one method emits
    (= the exec.launch fault-point hits), then the 3-method run dies
    exactly at MIN's spot-level seam — after SUM's row persisted,
    after MIN's plan was recorded, before MIN's launch. Run 2 resumes:
    SUM's row is reused WITHOUT re-entering the core, MIN and MAX
    measure fresh. The exec.* join across both runs is the
    zero-duplicate-launch audit: the interrupted plan shows
    plans=2/launches=1/done=1, the resumed row plans=1/launches=1."""
    out = tmp_path / "spot.json"
    led = tmp_path / "ledger.jsonl"

    # calibrate: how many plans does one clean SUM spot run?
    cal = _spot(tmp_path / "cal.json",
                _env(ledger=tmp_path / "cal.jsonl"), methods="SUM")
    assert cal.returncode == 0, cal.stderr
    hits_per_method = sum(1 for e in _events(tmp_path / "cal.jsonl")
                          if e["ev"] == "exec.plan")
    assert hits_per_method >= 1

    # run 1: die between MIN's exec.plan record and its launch
    faults = {"exec.launch": {"after": hits_per_method,
                              "action": "exit", "code": 3}}
    p1 = _spot(out, _env(faults=faults, ledger=led))
    assert p1.returncode == 3, p1.stderr
    doc1 = json.loads(out.read_text())
    assert doc1["complete"] is False
    assert [r["method"] for r in doc1["rows"]] == ["SUM"]

    # run 2: no faults — resume through the same core
    p2 = _spot(out, _env(ledger=led))
    assert p2.returncode == 0, p2.stderr
    assert "resumed from prior artifact" in p2.stderr
    doc2 = json.loads(out.read_text())
    assert doc2["complete"] is True
    assert [r["method"] for r in doc2["rows"]] == ["SUM", "MIN", "MAX"]
    # the reused row is byte-identical to the one run 1 persisted
    assert doc2["rows"][0] == doc1["rows"][0]

    # the ledger join across both runs (docs/EXECUTOR.md audit)
    evs = _events(led)
    fires = [e for e in evs if e["ev"] == "fault.fire"]
    assert fires and fires[0]["point"] == "exec.launch"

    def count(ev, surface):
        return sum(1 for e in evs
                   if e["ev"] == ev and e.get("surface") == surface)

    # SUM: persisted in run 1, RESUMED in run 2 — one plan ever
    assert (count("exec.plan", "spot/sum"),
            count("exec.launch", "spot/sum"),
            count("exec.done", "spot/sum")) == (1, 1, 1)
    # MIN: planned twice (run 1's record died at the seam), launched
    # exactly once — the zero-duplicate-launch contract
    assert (count("exec.plan", "spot/min"),
            count("exec.launch", "spot/min"),
            count("exec.done", "spot/min")) == (2, 1, 1)
    assert (count("exec.plan", "spot/max"),
            count("exec.launch", "spot/max"),
            count("exec.done", "spot/max")) == (1, 1, 1)
    # every completed launch (spot-level AND nested chain plans)
    # closed ok: the death fell between plan and launch, never inside
    assert all(e["ok"] for e in evs if e["ev"] == "exec.done")

    # the timeline's exec section sees the same join per surface
    from tpu_reductions.obs.timeline import exec_summary
    s = exec_summary(evs)
    by = {r["surface"]: r for r in s["surfaces"]}
    assert by["spot/min"]["plans"] == 2
    assert by["spot/min"]["done"] == 1
    assert by["spot/sum"]["plans"] == by["spot/sum"]["done"] == 1
    assert s["failures"] == 0
    # plans exceed launches by exactly the one interrupted record
    assert s["plans"] == s["launches"] + 1 == s["done"] + 1
