"""Property tests for flow/callgraph.py alias resolution.

Randomized small modules (seeded `random.Random`, deterministic per
test run) exercise the binding table the whole-program layers stand
on: plain module imports, `import m as alias`, `from m import f`,
from-import REBINDING (`from m import f as g`), `from pkg import
leafmodule`, and class targets resolving to `__init__`. For every
generated call the resolved fqn must match the generation plan — a
resolver regression here silently unlinks the call graph and turns
the flow/conc rules into false negatives, which is why this gets the
randomized treatment instead of a handful of hand fixtures
(docs/LINT.md "flow layer").
"""

import random

from tpu_reductions.lint.flow.callgraph import Project, extract_module

LIB_MODULE = "proj.lib"

# alias styles: (import-line template, call template). `{fn}` is the
# callee name in proj.lib, `{alias}` a random local alias.
STYLES = [
    ("import proj.lib",              "proj.lib.{fn}()"),
    ("import proj.lib as {alias}",   "{alias}.{fn}()"),
    ("from proj import lib",         "lib.{fn}()"),
    ("from proj import lib as {alias}", "{alias}.{fn}()"),
    ("from proj.lib import {fn}",    "{fn}()"),
    ("from proj.lib import {fn} as {alias}", "{alias}()"),
]


def _lib_source(fns):
    out = []
    for fn in fns:
        out.append(f"def {fn}():\n    pass\n\n")
    out.append("class Widget:\n"
               "    def __init__(self):\n"
               "        pass\n"
               "\n"
               "    def spin(self):\n"
               "        pass\n")
    return "\n".join(out)


def _project(caller_src):
    mods = {}
    fns = [f"fn_{i}" for i in range(6)]
    for name, src in ((LIB_MODULE, _lib_source(fns)),
                      ("proj.app", caller_src)):
        mods[name] = extract_module(
            src, name, name.replace(".", "/") + ".py", is_pkg=False)
    assert not mods["proj.app"].parse_error
    return Project(mods), fns


def _resolved(project, caller="proj.app"):
    """qualname -> [resolved fqn or None per call site] for the caller
    module, skipping unresolved noise (builtins etc.)."""
    mi = project.modules[caller]
    out = {}
    for fi in mi.functions.values():
        out[fi.qualname] = [project.resolve_target(c.target)
                            for c in fi.calls]
    return out


def test_alias_styles_all_resolve():
    rng = random.Random(0xC0FFEE)
    for trial in range(40):
        style_i = rng.randrange(len(STYLES))
        imp_t, call_t = STYLES[style_i]
        fn = f"fn_{rng.randrange(6)}"
        alias = f"alias_{rng.randrange(1000)}"
        imp = imp_t.format(fn=fn, alias=alias)
        call = call_t.format(fn=fn, alias=alias)
        src = (f"{imp}\n"
               "\n"
               "def entry():\n"
               f"    {call}\n")
        project, _ = _project(src)
        got = _resolved(project)["entry"]
        want = f"{LIB_MODULE}::{fn}"
        assert got == [want], (trial, imp, call, got)


def test_many_aliases_one_module_random_interleaving():
    """Several alias styles of the SAME library coexist in one module;
    every call still resolves to the one true definition."""
    rng = random.Random(7)
    for trial in range(20):
        picks = [rng.randrange(len(STYLES)) for _ in range(3)]
        lines, calls, wants = [], [], []
        for j, si in enumerate(picks):
            imp_t, call_t = STYLES[si]
            fn = f"fn_{rng.randrange(6)}"
            alias = f"a{j}_{rng.randrange(100)}"
            lines.append(imp_t.format(fn=fn, alias=alias))
            calls.append(call_t.format(fn=fn, alias=alias))
            wants.append(f"{LIB_MODULE}::{fn}")
        body = "\n".join(f"    {c}" for c in calls)
        src = "\n".join(lines) + "\n\ndef entry():\n" + body + "\n"
        project, _ = _project(src)
        assert _resolved(project)["entry"] == wants, (trial, src)


def test_from_import_rebinding_shadows_earlier_binding():
    """A later `from proj.lib import X as g` rebinds an earlier `g`;
    resolution follows the LAST binding in module order (the same
    rule Python applies at runtime for module-level imports)."""
    rng = random.Random(99)
    for _ in range(20):
        first, second = rng.sample(range(6), 2)
        src = (f"from proj.lib import fn_{first} as g\n"
               f"from proj.lib import fn_{second} as g\n"
               "\n"
               "def entry():\n"
               "    g()\n")
        project, _ = _project(src)
        assert _resolved(project)["entry"] == \
            [f"{LIB_MODULE}::fn_{second}"]


def test_class_target_resolves_to_init():
    for imp, ctor in (
            ("from proj.lib import Widget", "Widget()"),
            ("import proj.lib", "proj.lib.Widget()"),
            ("from proj.lib import Widget as W", "W()")):
        src = (f"{imp}\n"
               "\n"
               "def entry():\n"
               f"    {ctor}\n")
        project, _ = _project(src)
        assert _resolved(project)["entry"] == \
            [f"{LIB_MODULE}::Widget.__init__"]


def test_local_instance_method_calls_resolve():
    """`w = Widget(); w.spin()` links to Widget.spin — the resolution
    step the conc layer's ServeEngine driver fixtures depend on."""
    src = ("from proj.lib import Widget\n"
           "\n"
           "def entry():\n"
           "    w = Widget()\n"
           "    w.spin()\n")
    project, _ = _project(src)
    got = _resolved(project)["entry"]
    assert f"{LIB_MODULE}::Widget.spin" in got


def test_unknown_names_never_misresolve():
    """Random identifiers that were never imported must resolve to
    None, not accidentally latch onto a library function."""
    rng = random.Random(1234)
    for _ in range(30):
        name = "ghost_" + "".join(rng.choice("abcdef")
                                  for _ in range(8))
        src = ("import proj.lib\n"
               "\n"
               "def entry():\n"
               f"    {name}()\n")
        project, _ = _project(src)
        assert _resolved(project)["entry"] == [None]
