"""Flight-recorder unit coverage (tpu_reductions/obs/): ledger
crash-safety contracts, span semantics, seam events, timeline
attribution math, the WINDOW_SUMMARY table, both producers against the
one grammar, and the no-timing-distortion guarantee
(docs/OBSERVABILITY.md)."""

import json
import os
import subprocess
import time
from pathlib import Path

import pytest

from tpu_reductions.lint.grammar import EVENT_NAME_RE, EVENT_ROW_RE
from tpu_reductions.obs import ledger
from tpu_reductions.obs.spans import span
from tpu_reductions.obs.timeline import (analyze_session, main as
                                         timeline_main, read_ledger,
                                         split_sessions,
                                         summarize, summary_markdown)

REPO = Path(__file__).resolve().parent.parent


@pytest.fixture(autouse=True)
def _isolated_ledger(monkeypatch):
    """Every test starts unarmed with a clean env and leaves nothing
    armed behind (the module holds a process-global fd)."""
    monkeypatch.delenv("TPU_REDUCTIONS_LEDGER", raising=False)
    monkeypatch.delenv("TPU_REDUCTIONS_OBS_DISABLE", raising=False)
    ledger.disarm()
    yield
    ledger.disarm()


def _lines(path):
    return [json.loads(line) for line in
            Path(path).read_text().splitlines() if line.strip()]


# ---------------------------------------------------------------- ledger

def test_unarmed_emit_is_noop(tmp_path):
    assert not ledger.armed()
    assert ledger.emit("x.y", a=1) is False


def test_arm_emit_shape_and_grammar(tmp_path, monkeypatch):
    led = tmp_path / "l.jsonl"
    monkeypatch.setenv("TPU_REDUCTIONS_LEDGER", str(led))
    assert ledger.arm_session("unit.test", argv=["--a"]) == str(led)
    assert ledger.emit("a.b", n=3, s="txt", none_field=None)
    rows = _lines(led)
    assert rows[0]["ev"] == "session.start"
    assert rows[0]["prog"] == "unit.test"
    assert rows[1] == {**rows[1], "ev": "a.b", "n": 3, "s": "txt",
                       "none_field": None}
    for raw in led.read_text().splitlines():
        assert EVENT_ROW_RE.match(raw), raw
        assert EVENT_NAME_RE.match(json.loads(raw)["ev"])


def test_disable_env_hard_off(tmp_path, monkeypatch):
    monkeypatch.setenv("TPU_REDUCTIONS_LEDGER", str(tmp_path / "l"))
    monkeypatch.setenv("TPU_REDUCTIONS_OBS_DISABLE", "1")
    assert ledger.arm() is None
    assert not ledger.armed()


def test_emit_never_raises_and_disarms_on_io_error(tmp_path,
                                                   monkeypatch):
    led = tmp_path / "l.jsonl"
    assert ledger.arm(led)
    monkeypatch.setattr(os, "write",
                        lambda *a: (_ for _ in ()).throw(OSError("x")))
    assert ledger.emit("a.b") is False      # swallowed, not raised
    monkeypatch.undo()
    assert not ledger.armed()               # disarmed after the failure


def test_invalid_event_name_dropped(tmp_path):
    led = tmp_path / "l.jsonl"
    assert ledger.arm(led)
    assert ledger.emit("Bad Name!") is False
    assert ledger.emit("good.name") is True
    assert [r["ev"] for r in _lines(led)] == ["good.name"]


def test_nonfinite_fields_serialize_null(tmp_path):
    led = tmp_path / "l.jsonl"
    assert ledger.arm(led)
    assert ledger.emit("a.b", bad=float("nan"), worse=float("inf"))
    row = _lines(led)[0]
    assert row["bad"] is None and row["worse"] is None


def test_emit_attaches_heartbeat_phase(tmp_path):
    from tpu_reductions.utils import heartbeat
    led = tmp_path / "l.jsonl"
    assert ledger.arm(led)
    heartbeat.reset()
    with heartbeat.guard("staging"):
        ledger.emit("inside.guard")
    ledger.emit("outside.guard")
    rows = {r["ev"]: r for r in _lines(led)}
    assert rows["inside.guard"]["phase"] == "staging"
    assert "phase" not in rows["outside.guard"]
    # the guard itself recorded its transitions
    phases = [(r.get("prev"), r.get("phase")) for r in _lines(led)
              if r["ev"] == "hb.phase"]
    assert (None, "staging") in phases and ("staging", None) in phases


# ----------------------------------------------------------------- spans

def test_span_emits_start_end_with_duration(tmp_path):
    assert ledger.arm(tmp_path / "l.jsonl")
    with span("work", item=1):
        pass
    rows = _lines(tmp_path / "l.jsonl")
    assert [r["ev"] for r in rows] == ["work.start", "work.end"]
    assert rows[1]["dur_s"] >= 0 and rows[1]["item"] == 1


def test_span_records_error_and_reraises(tmp_path):
    assert ledger.arm(tmp_path / "l.jsonl")
    with pytest.raises(ValueError):
        with span("work"):
            raise ValueError("boom")
    end = _lines(tmp_path / "l.jsonl")[-1]
    assert end["ev"] == "work.end" and "ValueError: boom" in end["error"]


# ------------------------------------------------------------ seam events

def test_retry_events(tmp_path):
    from tpu_reductions.utils.retry import retry_device_call
    assert ledger.arm(tmp_path / "l.jsonl")
    calls = {"n": 0}

    def flaky():
        calls["n"] += 1
        if calls["n"] == 1:
            raise RuntimeError("flap")
        return 42

    assert retry_device_call(flaky, retries=2, _sleep=lambda s: None,
                             _tunneled=lambda: True,
                             _alive=lambda: True) == 42
    rows = [r for r in _lines(tmp_path / "l.jsonl")
            if r["ev"] == "retry.attempt"]
    assert len(rows) == 1
    assert rows[0]["attempt"] == 1 and "flap" in rows[0]["error"]
    assert rows[0]["delay_s"] > 0


def test_retry_fatal_event_on_dead_relay(tmp_path):
    from tpu_reductions.utils.retry import retry_device_call
    assert ledger.arm(tmp_path / "l.jsonl")
    with pytest.raises(RuntimeError):
        retry_device_call(lambda: (_ for _ in ()).throw(
            RuntimeError("dead")), retries=2, _sleep=lambda s: None,
            _tunneled=lambda: True, _alive=lambda: False)
    fatal = [r for r in _lines(tmp_path / "l.jsonl")
             if r["ev"] == "retry.fatal"]
    assert fatal and fatal[0]["reason"] == "relay-dead"


def test_checkpoint_events(tmp_path):
    from tpu_reductions.bench.resume import Checkpoint
    assert ledger.arm(tmp_path / "l.jsonl")
    out = tmp_path / "art.json"
    ck = Checkpoint(out, {"n": 4}, key_fn=lambda r: r["k"])
    ck.add({"k": "a", "status": "PASSED"})
    ck.finalize()
    # re-open the INTERRUPTED shape: rewrite as complete: false first
    data = json.loads(out.read_text())
    data["complete"] = False
    out.write_text(json.dumps(data))
    ck2 = Checkpoint(out, {"n": 4}, key_fn=lambda r: r["k"])
    assert ck2.resume("a") is not None
    evs = [r["ev"] for r in _lines(tmp_path / "l.jsonl")]
    assert evs.count("artifact.persist") == 2      # add + finalize
    assert "resume.decision" in evs and "resume.reuse" in evs
    modes = [r["mode"] for r in _lines(tmp_path / "l.jsonl")
             if r["ev"] == "resume.decision"]
    assert modes == ["fresh", "resume"]


def test_staging_chunk_events(tmp_path):
    import numpy as np

    from tpu_reductions.utils.staging import device_put_chunked
    assert ledger.arm(tmp_path / "l.jsonl")
    flat = np.arange(1024, dtype=np.int32)
    device_put_chunked(flat, 8, 128, 0, chunk_bytes=2 * 128 * 4)
    evs = [r["ev"] for r in _lines(tmp_path / "l.jsonl")]
    assert evs[0] == "staging.start"
    assert evs.count("staging.chunk") == 4         # 8 rows / 2-row step
    assert "staging.end" in evs


def test_fault_fire_event(tmp_path, monkeypatch):
    from tpu_reductions.faults import inject
    assert ledger.arm(tmp_path / "l.jsonl")
    monkeypatch.setenv(inject.ENV_VAR,
                       json.dumps({"p.x": {"action": "note"}}))
    inject.reset()
    assert inject.fault_point("p.x") == {"action": "note"}
    inject.reset()
    rows = _lines(tmp_path / "l.jsonl")
    assert rows[0]["ev"] == "fault.fire"
    assert rows[0]["point"] == "p.x" and rows[0]["action"] == "note"


# --------------------------------------------- timing: no distortion

def test_chain_trip_events_and_undistorted_slope(tmp_path):
    """The acceptance guarantee: chained slopes unchanged within noise
    with the recorder ARMED — a deterministic sleep-based chained fn
    must still measure its per-iteration cost, and every trip must land
    as an event AFTER its timed window."""
    from tpu_reductions.utils import heartbeat
    from tpu_reductions.utils.timing import time_chained
    heartbeat.reset()
    assert ledger.arm(tmp_path / "l.jsonl")
    per_iter = 0.002

    def chained(x, k):
        time.sleep(per_iter * k)
        return x

    sw = time_chained(chained, 0, k_lo=1, k_hi=6, reps=3,
                      materialize=lambda x: x)
    assert abs(sw.median_s - per_iter) < per_iter * 0.75
    rows = _lines(tmp_path / "l.jsonl")
    trips = [r for r in rows if r["ev"] == "chain.trip"]
    slopes = [r for r in rows if r["ev"] == "chain.slope"]
    assert len(trips) == 2 + 2 * 3 and len(slopes) == 3
    assert trips[0]["phase"] == "compile"          # first trip compiles
    assert all(t["dur_s"] > 0 for t in trips)


# -------------------------------------------------------------- timeline

def _mk_ledger(path, events):
    with open(path, "w") as f:
        for e in events:
            f.write(json.dumps(e) + "\n")


def test_timeline_attribution_math(tmp_path):
    led = tmp_path / "l.jsonl"
    t0 = 1000.0
    _mk_ledger(led, [
        {"t": t0, "ev": "session.start", "pid": 1, "prog": "x"},
        {"t": t0 + 1, "ev": "hb.phase", "pid": 1, "phase": "compile",
         "prev": None},
        {"t": t0 + 5, "ev": "hb.phase", "pid": 1, "phase": "chained",
         "prev": "compile"},
        {"t": t0 + 8, "ev": "hb.phase", "pid": 1, "phase": None,
         "prev": "chained"},
        {"t": t0 + 9, "ev": "session.end", "pid": 1},
    ])
    events, torn = read_ledger(led)
    assert torn == 0
    s = summarize(led, events, torn)["sessions"][0]
    assert s["phases_s"]["host"] == pytest.approx(2.0)   # 0..1 + 8..9
    assert s["phases_s"]["compile"] == pytest.approx(4.0)
    assert s["phases_s"]["measure"] == pytest.approx(3.0)
    assert s["end"] == "end"
    assert s["utilization"]["compile"] == pytest.approx(4 / 9, abs=1e-3)


def test_timeline_stall_carved_from_phase_bucket(tmp_path):
    led = tmp_path / "l.jsonl"
    t0 = 1000.0
    _mk_ledger(led, [
        {"t": t0, "ev": "session.start", "pid": 2, "prog": "x"},
        {"t": t0 + 1, "ev": "hb.phase", "pid": 2, "phase": "device",
         "prev": None},
        {"t": t0 + 11, "ev": "watchdog.exit", "pid": 2, "code": 4,
         "age_s": 8.0, "phase": "device", "relay": "alive"},
    ])
    events, torn = read_ledger(led)
    s = summarize(led, events, torn)["sessions"][0]
    assert s["end"] == "exit 4"
    assert s["phases_s"]["stalled"] == pytest.approx(8.0)
    assert s["phases_s"]["measure"] == pytest.approx(2.0)


def test_timeline_retry_carved_from_host(tmp_path):
    led = tmp_path / "l.jsonl"
    _mk_ledger(led, [
        {"t": 0.0, "ev": "session.start", "pid": 3, "prog": "x"},
        {"t": 1.0, "ev": "retry.attempt", "pid": 3, "delay_s": 2.0},
        {"t": 4.0, "ev": "session.end", "pid": 3},
    ])
    events, torn = read_ledger(led)
    s = summarize(led, events, torn)["sessions"][0]
    assert s["phases_s"]["retrying"] == pytest.approx(2.0)
    assert s["phases_s"]["host"] == pytest.approx(2.0)


def test_timeline_counts_torn_lines_and_survives_them(tmp_path):
    led = tmp_path / "l.jsonl"
    _mk_ledger(led, [{"t": 1.0, "ev": "session.start", "pid": 4}])
    with open(led, "a") as f:
        f.write('{"t": 2.0, "ev": "trunc')       # torn mid-write
    events, torn = read_ledger(led)
    assert torn == 1 and len(events) == 1
    assert "1 torn line(s)" in summary_markdown(
        summarize(led, events, torn)) or summarize(
        led, events, torn)["torn_lines"] == 1


def test_timeline_splits_sessions_per_pid_and_start(tmp_path):
    events = [
        {"t": 0.0, "ev": "watcher.arm", "pid": 9, "src": "shell"},
        {"t": 1.0, "ev": "session.start", "pid": 5, "prog": "a"},
        {"t": 2.0, "ev": "session.end", "pid": 5},
        {"t": 3.0, "ev": "session.start", "pid": 6, "prog": "b"},
    ]
    sessions = split_sessions(events)
    assert len(sessions) == 3
    assert analyze_session(sessions[0])["prog"] is None   # shell pseudo
    assert analyze_session(sessions[1])["prog"] == "a"
    assert analyze_session(sessions[2])["end"] == "cut"   # no terminal


def test_timeline_cli_json_and_summary_md(tmp_path, capsys):
    led = tmp_path / "l.jsonl"
    _mk_ledger(led, [
        {"t": 0.0, "ev": "session.start", "pid": 7, "prog": "spot"},
        {"t": 1.0, "ev": "session.end", "pid": 7},
    ])
    out = tmp_path / "summary.json"
    assert timeline_main([str(led), "--json", str(out),
                          "--summary-md"]) == 0
    printed = capsys.readouterr().out
    assert "window utilization (flight recorder)" in printed
    assert "| spot (pid 7) |" in printed
    summary = json.loads(out.read_text())
    assert summary["sessions"][0]["prog"] == "spot"
    assert timeline_main([str(tmp_path / "absent.jsonl")]) == 1


# ------------------------------------------------------- shell producer

def test_shell_emitter_matches_python_grammar(tmp_path):
    led = tmp_path / "shell.jsonl"
    subprocess.run(
        ["bash", "-c",
         f'source "{REPO}/scripts/obs_event.sh"; '
         "obs_event step.start name='double scoreboard' budget=300; "
         "obs_event step.end name=x rc=0 status=ok"],
        env={**os.environ, "TPU_REDUCTIONS_LEDGER": str(led)},
        check=True, timeout=30)
    raws = led.read_text().splitlines()
    assert len(raws) == 2
    for raw in raws:
        assert EVENT_ROW_RE.match(raw), raw
        rec = json.loads(raw)
        assert rec["src"] == "shell"
    assert json.loads(raws[0])["name"] == "double scoreboard"
    assert json.loads(raws[0])["budget"] == 300


def test_shell_emitter_noop_without_ledger(tmp_path):
    r = subprocess.run(
        ["bash", "-c",
         f'source "{REPO}/scripts/obs_event.sh"; obs_event x.y; '
         "echo done"],
        env={k: v for k, v in os.environ.items()
             if k != "TPU_REDUCTIONS_LEDGER"},
        capture_output=True, text=True, timeout=30)
    assert r.returncode == 0 and "done" in r.stdout


# ----------------------------------------------------- bench.py satellite

def test_bench_outage_event_carries_health_verdict(tmp_path,
                                                   monkeypatch,
                                                   capsys):
    import bench
    monkeypatch.chdir(tmp_path)
    led = tmp_path / "l.jsonl"
    monkeypatch.setenv("TPU_REDUCTIONS_LEDGER", str(led))
    health = tmp_path / "health.json"
    health.write_text(json.dumps(
        {"verdict": "STALLED", "relay": "alive", "ts": time.time()}))
    monkeypatch.setenv("TPU_REDUCTIONS_HEALTH_FILE", str(health))
    monkeypatch.setattr(bench, "_device_probe",
                        lambda platform=None: "probe hung")
    assert bench.main([]) == 1
    rows = _lines(led)
    outage = next(r for r in rows if r["ev"] == "bench.outage")
    assert outage["outage"] == "probe hung"
    assert outage["health"]["verdict"] == "STALLED"
    assert outage["health"]["stale"] is False
    # the fallback metric line is in the record too
    assert any(r["ev"] == "bench.metric" for r in rows)


def test_bench_metric_event_on_cpu_run(tmp_path, monkeypatch,
                                       stable_chained_timing):
    import bench
    monkeypatch.chdir(tmp_path)
    led = tmp_path / "l.jsonl"
    monkeypatch.setenv("TPU_REDUCTIONS_LEDGER", str(led))
    rc = bench.main(["--n", "65536", "--iterations", "16",
                     "--platform", "cpu"])
    assert rc == 0
    metric = [r for r in _lines(led) if r["ev"] == "bench.metric"]
    assert metric and metric[0]["unit"] == "GB/s"
    assert metric[0]["value"] > 0


# ------------------------------------------------------- ledger rotation

def test_ledger_rotation_caps_active_file(tmp_path, monkeypatch):
    """TPU_REDUCTIONS_LEDGER_MAX_BYTES (ISSUE 8 satellite): the active
    file rotates whole to `.1` before the cap is crossed, stays
    crash-safe (every line in BOTH files parses), and the newest events
    land in the fresh active file."""
    led = tmp_path / "l.jsonl"
    monkeypatch.setenv("TPU_REDUCTIONS_LEDGER_MAX_BYTES", "256")
    assert ledger.arm(led)
    for i in range(30):
        assert ledger.emit("a.b", i=i)
    rolled = tmp_path / "l.jsonl.1"
    assert rolled.exists()
    assert led.stat().st_size <= 256
    from tpu_reductions.lint.grammar import EVENT_ROW_RE
    for f in (led, rolled):
        for raw in f.read_text().splitlines():
            assert EVENT_ROW_RE.match(raw), raw
    # the newest event is in the active file, never lost to rotation
    assert _lines(led)[-1]["i"] == 29


def test_ledger_rotation_off_by_default(tmp_path, monkeypatch):
    monkeypatch.delenv("TPU_REDUCTIONS_LEDGER_MAX_BYTES", raising=False)
    led = tmp_path / "l.jsonl"
    assert ledger.arm(led)
    for i in range(50):
        assert ledger.emit("a.b", i=i)
    assert not (tmp_path / "l.jsonl.1").exists()
    assert len(_lines(led)) == 50
