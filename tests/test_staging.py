"""Chunked host->device staging (utils/staging.py): single multi-GiB
transfer messages killed the tunnel relay in both round-2 live windows;
bounded per-message staging must be bit-identical to the plain path.
"""

import jax.numpy as jnp
import numpy as np
import pytest

from tpu_reductions.ops.pallas_reduce import (choose_tiling,
                                              padded_2d_shape,
                                              stage_padded)
from tpu_reductions.ops.registry import get_op
from tpu_reductions.utils.staging import (device_put_chunked,
                                          maybe_chunked_stage)


@pytest.mark.parametrize("dtype", ["int32", "float32", "bfloat16"])
@pytest.mark.parametrize("method", ["SUM", "MIN", "MAX"])
@pytest.mark.parametrize("n", [1, 100, 4097, 65_536])
def test_chunked_equals_plain_staging(dtype, method, n):
    """Force tiny chunks: the chunked result must equal the one-message
    stage_padded output exactly, identity padding included."""
    op = get_op(method)
    rng = np.random.default_rng(n)
    if dtype == "int32":
        x = rng.integers(-1000, 1000, n, dtype=np.int32)
    else:
        x = rng.uniform(-1, 1, n).astype(
            jnp.bfloat16 if dtype == "bfloat16" else np.float32)
    tm, p, t = choose_tiling(n, 32, 8)
    plain = stage_padded(x, tm, p, t, op)
    rows, lanes = padded_2d_shape(n, tm, p, t)
    chunked = device_put_chunked(x, rows, lanes, op.identity(x.dtype),
                                 chunk_bytes=257)  # odd, tiny: many
    # messages with a ragged tail
    assert chunked.shape == plain.shape and chunked.dtype == plain.dtype
    np.testing.assert_array_equal(np.asarray(chunked, dtype=np.float32),
                                  np.asarray(plain, dtype=np.float32))


def test_maybe_chunked_threshold():
    x = np.arange(1024, dtype=np.int32)
    # under threshold -> None (caller keeps the single-message path)
    assert maybe_chunked_stage(x, 8, 128, np.int32(0)) is None
    # over (forced) threshold -> staged array
    out = maybe_chunked_stage(x, 8, 128, np.int32(0),
                              threshold_bytes=128, chunk_bytes=512)
    assert out is not None and out.shape == (8, 128)
    np.testing.assert_array_equal(np.asarray(out).ravel(), x)
    # non-numpy input (already a device array) -> None
    assert maybe_chunked_stage(jnp.asarray(x), 8, 128, 0) is None


def test_chunked_rejects_oversize_payload():
    with pytest.raises(ValueError):
        device_put_chunked(np.zeros(1025, np.int32), 8, 128, np.int32(0))


def test_chunked_reduces_correctly_end_to_end():
    """A chunk-staged payload must reduce to the oracle value through
    the normal kernel path (the staging contract is the kernel's
    padding contract)."""
    from tpu_reductions.ops.pallas_reduce import pallas_reduce

    n = 50_000
    x = np.random.default_rng(9).integers(-99, 99, n, dtype=np.int32)
    op = get_op("MIN")
    tm, p, t = choose_tiling(n, 32, 8)
    rows, lanes = padded_2d_shape(n, tm, p, t)
    staged = device_put_chunked(x, rows, lanes, op.identity(x.dtype),
                                chunk_bytes=4096)
    got = int(pallas_reduce(staged.ravel()[:n], "MIN", threads=32,
                            max_blocks=8))
    assert got == int(x.min())


def test_chunk_loop_interruption_leaves_no_partial_buffer(monkeypatch):
    """Satellite (ISSUE 2): a fault injected mid-payload — the round-2
    relay-death point — must leave NO partially-staged buffer that a
    subsequent resume could observe: the call raises before returning
    anything, and a clean re-invocation (the resume) produces the
    complete, bit-exact staged array despite the module-cached donated
    insert function having been used by the doomed attempt."""
    import json as _json

    from tpu_reductions.faults import inject
    from tpu_reductions.faults.inject import InjectedFault

    op = get_op("SUM")
    n = 4097
    x = np.arange(n, dtype=np.int32)
    tm, p, t = choose_tiling(n, 32, 8)
    rows, lanes = padded_2d_shape(n, tm, p, t)
    expected = stage_padded(x, tm, p, t, op)

    monkeypatch.setenv(inject.ENV_VAR, _json.dumps(
        {"staging.chunk": {"after": 2, "action": "raise"}}))
    inject.reset()
    with pytest.raises(InjectedFault):
        device_put_chunked(x, rows, lanes, op.identity(x.dtype),
                           chunk_bytes=512)   # dies chunks into the loop
    monkeypatch.delenv(inject.ENV_VAR)
    inject.reset()

    staged = device_put_chunked(x, rows, lanes, op.identity(x.dtype),
                                chunk_bytes=512)
    np.testing.assert_array_equal(np.asarray(staged),
                                  np.asarray(expected))


@pytest.mark.slow
def test_chunked_staging_at_true_hazard_scale():
    """The exact payload class that killed both round-2 windows —
    2^30 int32 = 4 GiB as ONE message — staged through the bounded
    16-chunk path at TRUE scale (not a lowered-threshold miniature).
    Off-chip this proves the code-path half of round-3 weak #6; the
    tunnel half still needs a live window. ~3 min on one core, hence
    slow-marked."""
    n = 1 << 30
    rows, lanes = n // 128, 128
    flat = np.arange(n, dtype=np.int32)
    arr = device_put_chunked(flat, rows, lanes, np.int32(0))
    a = np.asarray(arr)
    assert a.shape == (rows, lanes)
    assert a[0, 0] == 0 and a[-1, -1] == n - 1
    assert a[rows // 2, 64] == (rows // 2) * 128 + 64
