"""End-to-end chaos: the full death -> watchdog exit-3 -> watcher
re-arm -> resume pipeline on --platform=cpu (the acceptance scenario of
docs/RESILIENCE.md).

A scripted relay flap (faults/relay.FakeRelay) kills a real spot
subprocess mid-batch via the real watchdog (exit 3); re-invocation
resumes from the persisted rows; the final row set matches an
uninterrupted run's. The watcher layer (scripts/await_window.sh) is
driven the same way: an aborted session re-arms, a completed one
retires, and the session log is committed either way."""

import json
import os
import subprocess
import sys
import time
from pathlib import Path

import pytest

from tpu_reductions.faults.relay import FakeRelay
from tpu_reductions.faults.schedule import Phase

REPO = Path(__file__).resolve().parent.parent
SPOT_ARGS = ["--platform=cpu", "--type=int", "--methods=SUM,MIN,MAX",
             "--n=16384", "--iterations=8", "--chainreps=2"]


def _chaos_env(relay, marker, *, faults=None, interval="0.1", grace="2",
               ledger=None):
    env = {**os.environ,
           "TPU_REDUCTIONS_CHAOS_ARM": "1",
           "TPU_REDUCTIONS_RELAY_MARKER": str(marker),
           "TPU_REDUCTIONS_RELAY_PORTS": str(relay.port),
           "TPU_REDUCTIONS_WATCHDOG_INTERVAL_S": interval,
           "TPU_REDUCTIONS_WATCHDOG_GRACE": grace,
           # isolate the preflight health seam: a chaos subprocess must
           # neither read a real window's verdict nor leave one behind
           "TPU_REDUCTIONS_HEALTH_FILE": str(Path(marker).parent
                                             / "health.json")}
    env.pop("TPU_REDUCTIONS_FAULTS", None)
    env.pop("TPU_REDUCTIONS_LEDGER", None)
    if faults is not None:
        env["TPU_REDUCTIONS_FAULTS"] = json.dumps(faults)
    if ledger is not None:
        env["TPU_REDUCTIONS_LEDGER"] = str(ledger)
    return env


def _wait_for_rows(out: Path, n: int, timeout_s: float = 20.0):
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        try:
            rows = json.loads(out.read_text()).get("rows", [])
            if len(rows) >= n:
                return rows
        except (OSError, ValueError):
            pass
        time.sleep(0.05)
    pytest.fail(f"timed out waiting for {n} persisted row(s) in {out}")


def _bankable(rows):
    """(method, verified-or-waived) per row — the equivalence class
    resume reuse is decided on (bench/resume.default_reusable); the
    cross-run comparisons below use it because PASSED vs WAIVED is a
    per-run noise verdict at test scale, never a resume-logic fact."""
    return [(r["method"], r["status"] in ("PASSED", "WAIVED"))
            for r in rows]


def _spot(out: Path, env, extra=()):
    return subprocess.Popen(
        [sys.executable, "-m", "tpu_reductions.bench.spot",
         *SPOT_ARGS, *extra, f"--out={out}"],
        env=env, cwd=str(REPO),
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True)


def test_chaos_smoke_flap_exit3_then_resume_matches_uninterrupted(tmp_path):
    """THE acceptance pipeline, tier-1 sized: relay dies mid-batch ->
    real watchdog exits 3 with the measured prefix persisted ->
    re-invocation resumes those rows (zero re-measures) and completes
    -> the row set equals an uninterrupted control run's."""
    marker = tmp_path / "relay.marker"
    marker.write_text("tunneled\n")
    out = tmp_path / "spot.json"
    with FakeRelay() as relay:
        # method 2 (MIN) wedges in its device call — the round-2 death
        # shape — while the test flips the relay dead underneath it
        env = _chaos_env(relay, marker, faults={
            "bench.run": {"after": 1, "action": "stall", "seconds": 120}})
        proc = _spot(out, env)
        _wait_for_rows(out, 1)          # SUM verified and persisted
        relay.force("refuse")
        rc = proc.wait(timeout=60)
        stderr = proc.stderr.read()
        assert rc == 3, f"expected watchdog exit 3, got {rc}: {stderr}"
        assert "relay watchdog: relay is gone" in stderr
        interrupted = json.loads(out.read_text())
        assert interrupted["complete"] is False
        assert [r["method"] for r in interrupted["rows"]] == ["SUM"]

        # window 2: relay back, no faults — resume the banked row
        relay.force("accept")
        time.sleep(0.15)
        proc2 = _spot(out, _chaos_env(relay, marker))
        assert proc2.wait(timeout=60) == 0
        assert "resumed from prior artifact" in proc2.stderr.read()
        resumed = json.loads(out.read_text())
        assert resumed["complete"] is True
        assert resumed["rows"][0] == interrupted["rows"][0]  # reused row

        # uninterrupted control: identical final row set
        out2 = tmp_path / "control.json"
        proc3 = _spot(out2, _chaos_env(relay, marker))
        assert proc3.wait(timeout=60) == 0
        control = json.loads(out2.read_text())
    # statuses compare up to the verified-or-waived class the resume
    # machinery banks on (bench/resume.default_reusable): the chained
    # WAIVE-on-noise verdict is nondeterministic at this n under host
    # load (tests/conftest.py stable_chained_timing rationale), so two
    # INDEPENDENT subprocess runs may draw PASSED vs WAIVED
    # differently — row identity and bankability are the resume
    # contract, noise verdicts are not
    assert _bankable(resumed["rows"]) == _bankable(control["rows"])
    assert all(ok for _, ok in _bankable(resumed["rows"]))
    assert resumed["complete"] == control["complete"] is True


def test_chaos_stall_relay_heartbeat_exit4_then_resume(tmp_path):
    """ISSUE 3's previously-fatal scenario: the relay flips to `stall`
    (ports ACCEPT — the watchdog's port probe keeps saying alive — but
    nothing is serviced) while a benchmark's device work wedges. The
    old stack hung forever; the heartbeat trigger must exit 4 within
    the compressed deadline with the 'alive' port verdict attached,
    keep every previously-persisted row, and resume them
    byte-identically on re-invocation.

    ISSUE 4 acceptance rides the same scenario: both windows share one
    flight-recorder ledger, and the timeline CLI must reconstruct the
    full death narrative — arm -> compile -> staging -> stall ->
    heartbeat exit 4 -> resume — with per-phase wall-clock attribution
    (the stall carved into the 'stalled' bucket)."""
    marker = tmp_path / "relay.marker"
    marker.write_text("tunneled\n")
    out = tmp_path / "spot.json"
    led = tmp_path / "ledger.jsonl"
    with FakeRelay() as relay:
        env = _chaos_env(relay, marker, ledger=led, faults={
            "bench.run": {"after": 1, "action": "stall", "seconds": 120}})
        # compressed heartbeat deadlines: steady 5 s (legit cpu-test
        # device regions finish in well under that), compile 60 s (the
        # first-jit budget must never be what fires)
        env["TPU_REDUCTIONS_HEARTBEAT_DEADLINE_S"] = "5.0"
        env["TPU_REDUCTIONS_HEARTBEAT_COMPILE_DEADLINE_S"] = "60"
        proc = _spot(out, env)
        _wait_for_rows(out, 1)          # SUM verified and persisted
        relay.force("stall")            # wedged-but-ports-open
        rc = proc.wait(timeout=60)      # the old failure mode: forever
        stderr = proc.stderr.read()
        assert rc == 4, f"expected heartbeat exit 4, got {rc}: {stderr}"
        assert "HANG" in stderr
        # the port verdict is attached: ports were ALIVE when it fired
        assert "verdict at fire time: alive" in stderr
        interrupted = json.loads(out.read_text())
        assert interrupted["complete"] is False
        assert [r["method"] for r in interrupted["rows"]] == ["SUM"]

        # the stall clears; re-invocation resumes the banked row
        # byte-identically and completes the remaining methods
        relay.force("accept")
        time.sleep(0.15)
        proc2 = _spot(out, _chaos_env(relay, marker, ledger=led))
        assert proc2.wait(timeout=60) == 0
        assert "resumed from prior artifact" in proc2.stderr.read()
        resumed = json.loads(out.read_text())
    assert resumed["complete"] is True
    assert resumed["rows"][0] == interrupted["rows"][0]  # byte-identical
    assert [r["method"] for r in resumed["rows"]] == ["SUM", "MIN", "MAX"]

    # ---- flight-recorder reconstruction (ISSUE 4 acceptance) ----
    from tpu_reductions.obs.timeline import read_ledger, summarize
    events, torn = read_ledger(led)
    assert torn == 0                    # no torn lines under os._exit
    evs = [e["ev"] for e in events]
    # the narrative, in order: arm -> compile -> staging -> stall ->
    # exit 4; then the second window's resume
    assert "session.start" in evs and "watchdog.arm" in evs
    compiles = [e for e in events if e["ev"] == "hb.phase"
                and e.get("phase") == "compile"]
    assert compiles, "compile phase transitions must be recorded"
    assert "staging.stage" in evs
    stall = next(e for e in events if e["ev"] == "fault.fire")
    assert stall["action"] == "stall"
    exit4 = next(e for e in events if e["ev"] == "watchdog.exit")
    assert exit4["code"] == 4 and exit4["relay"] == "alive"
    assert exit4["age_s"] >= 5.0        # past the compressed deadline
    assert evs.index("fault.fire") < evs.index("watchdog.exit")
    assert "resume.reuse" in evs[evs.index("watchdog.exit"):]
    summary = summarize(led, events, torn)
    sessions = summary["sessions"]
    dead = next(s for s in sessions if s["end"] == "exit 4")
    alive = next(s for s in sessions if s["end"] == "end")
    # per-phase attribution: the stalled window spent most of its wall
    # clock in the carved 'stalled' bucket; the resume window reused
    # the banked row
    assert dead["phases_s"]["stalled"] >= 5.0
    assert dead["utilization"]["stalled"] > 0.3
    assert alive["reused_rows"] >= 1 and alive["persists"] >= 1


SWEEP_ARGS = ["--platform=cpu", "--ranks=2,4", "--methods=SUM",
              "--types=int", "--n=65536", "--retries=1"]


def _sweep(out_dir: Path, env):
    return subprocess.Popen(
        [sys.executable, "-m", "tpu_reductions.bench.sweep",
         *SWEEP_ARGS, f"--out-dir={out_dir}"],
        env=env, cwd=str(REPO),
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True)


def test_chaos_sweep_relay_death_midladder_resumes_rank_rows(tmp_path):
    """ISSUE 10 satellite: the rank-scaling sweep under a relay death
    BETWEEN ladder rungs. The `collective.hop` fault point wedges the
    second rung's launch (rank 4) while the test flips the relay dead —
    the watchdog exits 3 with the completed rank-2 rows persisted in
    `collective_sweep.json`, and the re-invoked sweep resumes those
    per-rank-count rows byte-identically (zero re-measures of rung 2)
    instead of restarting at the bottom of the ladder
    (docs/COLLECTIVES.md; docs/RESILIENCE.md fault-point table)."""
    marker = tmp_path / "relay.marker"
    marker.write_text("tunneled\n")
    out = tmp_path / "collective_sweep.json"
    with FakeRelay() as relay:
        # hop 1 (rank 2) measures clean; hop 2 (rank 4) wedges in its
        # launch — the relay-death-between-rungs shape
        env = _chaos_env(relay, marker, faults={
            "collective.hop": {"after": 1, "action": "stall",
                               "seconds": 120}})
        proc = _sweep(tmp_path, env)
        _wait_for_rows(out, 1)          # rank-2 row verified + persisted
        relay.force("refuse")
        rc = proc.wait(timeout=90)
        stderr = proc.stderr.read()
        assert rc == 3, f"expected watchdog exit 3, got {rc}: {stderr}"
        interrupted = json.loads(out.read_text())
        assert interrupted["complete"] is False
        assert {r["ranks"] for r in interrupted["rows"]} == {2}

        # window 2: relay back, no faults — the ladder resumes at rank 4
        relay.force("accept")
        time.sleep(0.15)
        proc2 = _sweep(tmp_path, _chaos_env(relay, marker))
        rc2 = proc2.wait(timeout=90)
        stderr2 = proc2.stderr.read()
        assert rc2 == 0, stderr2
        assert "resumed from prior artifact" in stderr2
        resumed = json.loads(out.read_text())
    assert resumed["complete"] is True
    # the banked rung is reused byte-identically, then the ladder climbs
    n2 = len(interrupted["rows"])
    assert resumed["rows"][:n2] == interrupted["rows"]
    assert [r["ranks"] for r in resumed["rows"][n2:]] == [4]
    assert all(r["status"] in ("PASSED", "WAIVED") for r in resumed["rows"])


def test_await_window_defers_on_non_live_preflight(tmp_path):
    """The wedge-aware polling loop: relay ports answer, but a
    preflight verdict of 4 (stall/wedge) must stop await_window from
    firing a hang-forever session — it logs the deferral and holds
    until the health verdict clears (here: the health file goes
    non-wedged), then fires."""
    _git(tmp_path, "init", "-q")
    _git(tmp_path, "config", "user.email", "t@t")
    _git(tmp_path, "config", "user.name", "t")
    marker = tmp_path / "relay.marker"
    marker.write_text("tunneled\n")
    health = tmp_path / "health.json"
    # scripted preflight: rc=4 while the health file says WEDGED, 0
    # after — the seam the real `python -m tpu_reductions.utils.
    # preflight` fills live (its own classification is covered in
    # tests/test_preflight.py)
    pf = tmp_path / "fake_preflight.sh"
    pf.write_text(
        "#!/usr/bin/env bash\n"
        'grep -q WEDGED "$TPU_REDUCTIONS_HEALTH_FILE" 2>/dev/null'
        ' && exit 4\n'
        "exit 0\n")
    pf.chmod(0o755)
    session = tmp_path / "fake_session.sh"
    session.write_text("#!/usr/bin/env bash\necho session-ran\nexit 0\n")
    session.chmod(0o755)
    health.write_text('{"verdict": "WEDGED", "ts": 0}\n')

    import threading

    def clear_health():
        time.sleep(3.0)
        health.write_text('{"verdict": "LIVE", "ts": 0}\n')

    with FakeRelay() as relay:
        env = {**os.environ,
               "AWAIT_ROOT": str(tmp_path),
               "SESSION_BIN": str(session),
               "PREFLIGHT_CMD": str(pf),
               "CHIP_LOG": "chip.log",
               "TPU_REDUCTIONS_HEALTH_FILE": str(health),
               "TPU_REDUCTIONS_RELAY_MARKER": str(marker),
               "TPU_REDUCTIONS_RELAY_PORTS": str(relay.port)}
        t = threading.Thread(target=clear_health, daemon=True)
        t.start()
        proc = subprocess.run(
            ["bash", str(REPO / "scripts" / "await_window.sh"), "1", "1"],
            env=env, cwd=str(tmp_path), capture_output=True, text=True,
            timeout=60)
        t.join()
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "preflight says NOT LIVE" in proc.stdout
    assert "deferring until it clears" in proc.stdout
    assert "health verdict cleared" in proc.stdout
    assert "session-ran" in proc.stdout + (tmp_path / "chip.log").read_text()


def test_transient_flap_is_retried_not_fatal(tmp_path):
    """A device call that fails while the relay still answers is a
    transient flap: the retry wrapper (utils/retry.py) re-runs it and
    the batch completes with every row measured — no exit 3, no FAILED
    row."""
    marker = tmp_path / "relay.marker"
    marker.write_text("tunneled\n")
    out = tmp_path / "spot.json"
    with FakeRelay() as relay:
        env = _chaos_env(relay, marker, faults={
            "bench.run": {"after": 1, "times": 1, "action": "raise"}})
        env["TPU_REDUCTIONS_DEVICE_RETRIES"] = "2"
        proc = _spot(out, env)
        rc = proc.wait(timeout=60)
        stderr = proc.stderr.read()
        assert rc == 0, stderr
        assert "retry: transient device-call failure" in stderr
    data = json.loads(out.read_text())
    assert [r["method"] for r in data["rows"]] == ["SUM", "MIN", "MAX"]
    assert all(r["status"] in ("PASSED", "WAIVED") for r in data["rows"])


def test_chaos_sigkill_midbatch_ledger_has_zero_torn_lines(tmp_path):
    """Ledger crash-safety (ISSUE 4 satellite): a SIGKILL-class death
    mid-batch (faults/inject.py action "exit" — os._exit with no
    cleanup, the same no-atexit shape as a real SIGKILL) must leave a
    ledger with ZERO torn/partial lines, and the timeline CLI must
    still reconstruct the run (first session 'cut', second 'end')."""
    marker = tmp_path / "relay.marker"
    marker.write_text("tunneled\n")
    out = tmp_path / "spot.json"
    led = tmp_path / "ledger.jsonl"
    with FakeRelay() as relay:
        env = _chaos_env(relay, marker, ledger=led, faults={
            "bench.run": {"after": 1, "action": "exit", "code": 9}})
        proc = _spot(out, env)
        rc = proc.wait(timeout=60)
        assert rc == 9, proc.stderr.read()
        interrupted = json.loads(out.read_text())
        assert interrupted["complete"] is False

        # second window, no faults: completes against the same ledger
        proc2 = _spot(out, _chaos_env(relay, marker, ledger=led))
        assert proc2.wait(timeout=60) == 0

    from tpu_reductions.obs.timeline import (read_ledger, summarize,
                                             main as timeline_main)
    events, torn = read_ledger(led)
    assert torn == 0, "ledger must have no torn lines under SIGKILL"
    assert events
    # every line byte-validates against the registered row grammar
    from tpu_reductions.lint.grammar import EVENT_ROW_RE
    for raw in led.read_text().splitlines():
        assert EVENT_ROW_RE.match(raw), raw
    sessions = summarize(led, events, torn)["sessions"]
    assert len(sessions) == 2
    # the killed run has no terminal event (no atexit under os._exit);
    # the fault that killed it is its last recorded fact
    assert sessions[0]["end"] == "cut"
    killed = [e for e in events if e.get("pid") == sessions[0]["pid"]]
    assert killed[-1]["ev"] == "fault.fire"
    assert killed[-1]["action"] == "exit"
    assert sessions[1]["end"] == "end"
    assert timeline_main([str(led)]) == 0


# ---------------------------------------------------------------------------
# Scheduler-under-chaos (ISSUE 5 satellite): the plan-and-execute layer
# (tpu_reductions/sched/) must survive the same deaths the per-task
# resume already does — a relay death mid-task (executor exit 3) and a
# stall-with-live-ports (exit 4) both persist the PLAN, a re-invocation
# resumes it, completed tasks are never re-measured (artifacts stay
# byte-identical), and the final row sets equal an uninterrupted
# control run's.
# ---------------------------------------------------------------------------

def _sched_tasks_file(tmp_path):
    """Two real spot tasks: 'quick' (one method) and 'batch' (three
    methods — the chaos fault plans target its second method)."""
    base = ("python -m tpu_reductions.bench.spot --platform=cpu "
            "--type=int --n=16384 --iterations=8 --chainreps=2 ")
    spec = [
        {"name": "quick", "value": 10, "budget_s": 60,
         "command": base + "--methods=SUM --out=quick.json",
         "artifacts": ["quick.json"], "done_artifact": "quick.json"},
        {"name": "batch", "value": 5, "budget_s": 60,
         "command": base + "--methods=SUM,MIN,MAX --out=batch.json",
         "artifacts": ["batch.json"], "done_artifact": "batch.json"},
    ]
    (tmp_path / "sched_tasks.json").write_text(json.dumps(spec))


def _sched_exec(tmp_path, env):
    return subprocess.Popen(
        [sys.executable, "-m", "tpu_reductions.sched",
         "--tasks=sched_tasks.json", "--state=sched_state.json"],
        env={**env, "PYTHONPATH": str(REPO)}, cwd=str(tmp_path),
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True)


def _sched_state(tmp_path):
    return json.loads((tmp_path / "sched_state.json").read_text())


def test_chaos_sched_relay_death_midplan_resumes_without_remeasuring(
        tmp_path):
    """Executor exit 3: the relay dies while task 'batch' wedges
    mid-method. The plan state persists ('quick' done, 'batch'
    aborted), the executor propagates the watchdog's code, and the
    re-invocation finishes ONLY the remaining work: quick.json is
    byte-identical afterwards, batch's banked SUM row is reused, and
    the final row set equals an uninterrupted control run's."""
    marker = tmp_path / "relay.marker"
    marker.write_text("tunneled\n")
    _sched_tasks_file(tmp_path)
    with FakeRelay() as relay:
        env = _chaos_env(relay, marker, faults={
            "bench.run": {"after": 1, "action": "stall", "seconds": 120}})
        proc = _sched_exec(tmp_path, env)
        _wait_for_rows(tmp_path / "batch.json", 1)   # SUM banked
        relay.force("refuse")
        rc = proc.wait(timeout=90)
        stderr = proc.stderr.read()
        assert rc == 3, f"expected executor exit 3, got {rc}: {stderr}"
        st = _sched_state(tmp_path)
        assert st["complete"] is False
        assert st["tasks"]["quick"]["status"] == "done"
        assert st["tasks"]["batch"]["status"] == "aborted"
        quick_bytes = (tmp_path / "quick.json").read_bytes()
        interrupted = json.loads((tmp_path / "batch.json").read_text())
        assert [r["method"] for r in interrupted["rows"]] == ["SUM"]

        # window 2: relay back, no faults — the PLAN resumes
        relay.force("accept")
        time.sleep(0.15)
        proc2 = _sched_exec(tmp_path, _chaos_env(relay, marker))
        rc2 = proc2.wait(timeout=90)
        assert rc2 == 0, proc2.stderr.read()
        st2 = _sched_state(tmp_path)
        assert st2["complete"] is True
        assert st2["tasks"]["batch"]["status"] == "done"
        # zero re-measurement of the completed unit
        assert (tmp_path / "quick.json").read_bytes() == quick_bytes
        resumed = json.loads((tmp_path / "batch.json").read_text())
        assert resumed["rows"][0] == interrupted["rows"][0]  # banked row

        # uninterrupted control: identical final row sets
        control_dir = tmp_path / "control"
        control_dir.mkdir()
        _sched_tasks_file(control_dir)
        proc3 = _sched_exec(control_dir, _chaos_env(relay, marker))
        assert proc3.wait(timeout=90) == 0, proc3.stderr.read()
        control = json.loads((control_dir / "batch.json").read_text())
    # bankability-class comparison, same rationale as the smoke-flap
    # test above: cross-run status equality is noise-sensitive
    assert _bankable(resumed["rows"]) == _bankable(control["rows"])
    assert all(ok for _, ok in _bankable(resumed["rows"]))
    assert resumed["complete"] == control["complete"] is True


def test_chaos_sched_stall_exit4_midplan_resumes(tmp_path):
    """Executor exit 4: the relay flips to `stall` (ports answer,
    nothing serviced) while 'batch' wedges — the task's heartbeat
    trigger exits 4, the executor propagates it with the plan
    persisted, and the re-invocation completes the plan without
    repeating 'quick'."""
    marker = tmp_path / "relay.marker"
    marker.write_text("tunneled\n")
    _sched_tasks_file(tmp_path)
    with FakeRelay() as relay:
        env = _chaos_env(relay, marker, faults={
            "bench.run": {"after": 1, "action": "stall", "seconds": 120}})
        env["TPU_REDUCTIONS_HEARTBEAT_DEADLINE_S"] = "5.0"
        env["TPU_REDUCTIONS_HEARTBEAT_COMPILE_DEADLINE_S"] = "60"
        proc = _sched_exec(tmp_path, env)
        _wait_for_rows(tmp_path / "batch.json", 1)
        relay.force("stall")
        rc = proc.wait(timeout=90)
        assert rc == 4, proc.stderr.read()
        st = _sched_state(tmp_path)
        assert st["complete"] is False
        assert st["tasks"]["quick"]["status"] == "done"
        assert st["tasks"]["batch"]["status"] == "aborted"
        quick_bytes = (tmp_path / "quick.json").read_bytes()

        relay.force("accept")
        time.sleep(0.15)
        proc2 = _sched_exec(tmp_path, _chaos_env(relay, marker))
        assert proc2.wait(timeout=90) == 0, proc2.stderr.read()
    st2 = _sched_state(tmp_path)
    assert st2["complete"] is True
    assert (tmp_path / "quick.json").read_bytes() == quick_bytes
    final = json.loads((tmp_path / "batch.json").read_text())
    assert [r["method"] for r in final["rows"]] == ["SUM", "MIN", "MAX"]


def _git(root, *args):
    subprocess.run(["git", *args], cwd=root, check=True,
                   capture_output=True)


def test_await_window_rearms_after_exit3_and_retires_on_complete(tmp_path):
    """The watcher half of the pipeline: an aborted session (rc=3, the
    watchdog's code) RE-ARMS the watcher; the next window's session
    completes (rc=0) and retires it; the session log is committed —
    and (ISSUE 4) the arm/fire/re-arm/retire decisions land in the
    flight-recorder ledger as watcher.* events."""
    _git(tmp_path, "init", "-q")
    _git(tmp_path, "config", "user.email", "t@t")
    _git(tmp_path, "config", "user.name", "t")
    marker = tmp_path / "relay.marker"
    marker.write_text("tunneled\n")
    led = tmp_path / "ledger.jsonl"
    session = tmp_path / "fake_session.sh"
    session.write_text(
        "#!/usr/bin/env bash\n"
        "echo run >> sessions.txt\n"
        'n=$(wc -l < sessions.txt)\n'
        '[ "$n" -le 1 ] && { echo "session aborts (flap)"; exit 3; }\n'
        'echo "session completes"; exit 0\n')
    session.chmod(0o755)
    with FakeRelay() as relay:
        env = {**os.environ,
               "AWAIT_ROOT": str(tmp_path),
               "SESSION_BIN": str(session),
               "CHIP_LOG": "chip.log",
               "TPU_REDUCTIONS_LEDGER": str(led),
               "TPU_REDUCTIONS_RELAY_MARKER": str(marker),
               "TPU_REDUCTIONS_RELAY_PORTS": str(relay.port)}
        proc = subprocess.run(
            ["bash", str(REPO / "scripts" / "await_window.sh"), "1", "1"],
            env=env, cwd=str(tmp_path), capture_output=True, text=True,
            timeout=60)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "re-arming (session rc=3" in proc.stdout
    assert (tmp_path / "sessions.txt").read_text().count("run") == 2
    # watcher narrative in the ledger: armed -> fired -> session died
    # (rc=3) -> re-armed -> fired -> retired on rc=0
    evs = [json.loads(line) for line in led.read_text().splitlines()]
    names = [e["ev"] for e in evs]
    assert names.index("watcher.arm") < names.index("watcher.fire")
    rearm = next(e for e in evs if e["ev"] == "watcher.rearm")
    assert rearm["rc"] == 3
    assert names[-1] == "watcher.retire"
    assert [e["rc"] for e in evs if e["ev"] == "watcher.session_end"] \
        == [3, 0]
    log_commits = subprocess.run(
        ["git", "log", "--oneline", "--", "chip.log"], cwd=tmp_path,
        capture_output=True, text=True).stdout.strip().splitlines()
    assert len(log_commits) >= 2   # one commit per session's log growth


def test_await_window_log_default_derives_from_round(tmp_path):
    """Satellite: no more stale r04 pins — the default session log name
    tracks the highest ROUND<N>.md in the repo."""
    (tmp_path / "ROUND7.md").write_text("# round 7\n")
    (tmp_path / "ROUND11.md").write_text("# round 11\n")
    marker = tmp_path / "relay.marker"
    marker.write_text("tunneled\n")
    env = {**os.environ,
           "AWAIT_ROOT": str(tmp_path),
           "TPU_REDUCTIONS_RELAY_MARKER": str(marker),
           # a port nothing listens on: the watcher must idle, hit the
           # 0-hour horizon, and exit 4 having named its log
           "TPU_REDUCTIONS_RELAY_PORTS": "1"}
    env.pop("CHIP_LOG", None)
    proc = subprocess.run(
        ["bash", str(REPO / "scripts" / "await_window.sh"), "1", "0"],
        env=env, cwd=str(tmp_path), capture_output=True, text=True,
        timeout=60)
    assert proc.returncode == 4
    assert "chip_session_r11.log" in proc.stdout


def test_await_window_untunneled_host_exits_clean(tmp_path):
    env = {**os.environ,
           "AWAIT_ROOT": str(tmp_path),
           "TPU_REDUCTIONS_RELAY_MARKER": str(tmp_path / "absent")}
    proc = subprocess.run(
        ["bash", str(REPO / "scripts" / "await_window.sh"), "1", "1"],
        env=env, cwd=str(tmp_path), capture_output=True, text=True,
        timeout=30)
    assert proc.returncode == 0
    assert "untunneled" in proc.stdout


@pytest.mark.slow
def test_slow_wall_clock_flap_schedule_kills_and_resumes(tmp_path):
    """The long-flap scenario on wall-clock phases (no test-driven
    force()): the relay schedule itself opens a window, dies for
    seconds mid-batch, and comes back — the watchdog exits 3 during
    the dead phase, and the post-flap re-invocation completes from the
    persisted prefix."""
    marker = tmp_path / "relay.marker"
    marker.write_text("tunneled\n")
    out = tmp_path / "spot.json"
    schedule = [Phase("accept", duration_s=3.0),
                Phase("refuse", duration_s=6.0),
                Phase("accept")]
    with FakeRelay(schedule) as relay:
        env = _chaos_env(relay, marker, interval="0.5", faults={
            # every method after the first wedges long enough to
            # straddle the schedule's dead phase
            "bench.run": {"after": 1, "action": "stall", "seconds": 30}})
        proc = _spot(out, env)
        rc = proc.wait(timeout=120)
        assert rc == 3, proc.stderr.read()
        interrupted = json.loads(out.read_text())
        assert interrupted["complete"] is False
        assert len(interrupted["rows"]) >= 1

        # wait out the dead phase; the relay flaps back on its own
        deadline = time.monotonic() + 30
        from tpu_reductions.utils.watchdog import probe_relay
        while probe_relay(ports=(relay.port,), timeout_s=0.3) != "alive":
            assert time.monotonic() < deadline
            time.sleep(0.2)
        proc2 = _spot(out, _chaos_env(relay, marker))
        assert proc2.wait(timeout=120) == 0
        final = json.loads(out.read_text())
    assert final["complete"] is True
    assert [r["method"] for r in final["rows"]] == ["SUM", "MIN", "MAX"]
    assert final["rows"][:len(interrupted["rows"])] == interrupted["rows"]
