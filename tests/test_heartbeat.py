"""Forward-progress heartbeat (utils/heartbeat.py) + the watchdog's
exit-4 hang trigger: the two tunnel failure modes the port probe
cannot see (stalled relay, wedged lease) must fire a prompt,
artifact-preserving exit instead of a forever-hang."""

import threading
import time

import pytest

from tpu_reductions.utils import heartbeat
from tpu_reductions.utils.heartbeat import HANG_EXIT_CODE


@pytest.fixture(autouse=True)
def _fresh_heartbeat():
    heartbeat.reset()
    yield
    heartbeat.reset()


def test_guard_marks_in_flight_and_balances():
    assert heartbeat.snapshot()["in_flight"] is False
    with heartbeat.guard("staging"):
        snap = heartbeat.snapshot()
        assert snap["in_flight"] is True
        assert snap["phase"] == "staging"
        assert snap["beats"] >= 1
    assert heartbeat.snapshot()["in_flight"] is False


def test_guards_nest_and_unwind_on_exception():
    with heartbeat.guard("device"):
        with heartbeat.guard("compile"):
            assert heartbeat.snapshot()["phase"] == "compile"
        assert heartbeat.snapshot()["phase"] == "device"
        with pytest.raises(RuntimeError):
            with heartbeat.guard("staging"):
                raise RuntimeError("boom")
        # the failed inner guard must not strand its phase
        assert heartbeat.snapshot()["phase"] == "device"
    assert heartbeat.snapshot()["in_flight"] is False


def test_tick_refreshes_mark_and_relabels_phase():
    with heartbeat.guard("compile"):
        time.sleep(0.05)
        assert heartbeat.snapshot()["age_s"] >= 0.04
        heartbeat.tick("steady")
        snap = heartbeat.snapshot()
        assert snap["age_s"] < 0.04
        assert snap["phase"] == "steady"


def test_tick_outside_guard_is_noop():
    heartbeat.tick("steady")
    snap = heartbeat.snapshot()
    assert snap["beats"] == 0 and snap["in_flight"] is False


def test_deadline_env_overrides(monkeypatch):
    monkeypatch.setenv("TPU_REDUCTIONS_HEARTBEAT_DEADLINE_S", "7")
    monkeypatch.setenv("TPU_REDUCTIONS_HEARTBEAT_COMPILE_DEADLINE_S", "42")
    assert heartbeat.deadline_for("steady") == 7.0
    assert heartbeat.deadline_for(heartbeat.PHASE_COMPILE) == 42.0
    monkeypatch.delenv("TPU_REDUCTIONS_HEARTBEAT_DEADLINE_S")
    assert heartbeat.deadline_for(None) == heartbeat.DEFAULT_DEADLINE_S


def test_suppress_fault_freezes_the_mark(monkeypatch):
    """The chaos seam: a scripted {'action': 'suppress'} on
    heartbeat.tick models a site that keeps looping while its progress
    marks stop landing — the deterministic way to starve the heartbeat
    without wall-clock sleeps (faults/inject.py)."""
    from tpu_reductions.faults import inject
    monkeypatch.setenv(inject.ENV_VAR,
                       '{"heartbeat.tick": {"action": "suppress"}}')
    inject.reset()
    try:
        with heartbeat.guard("device"):      # begin's mark: suppressed
            heartbeat.tick()
            heartbeat.tick()
            assert heartbeat.snapshot()["beats"] == 0
    finally:
        inject.reset()


def test_retry_device_call_runs_under_a_guard():
    from tpu_reductions.utils.retry import retry_device_call

    seen = {}

    def fn():
        seen.update(heartbeat.snapshot())
        return 7

    assert retry_device_call(fn, _tunneled=lambda: False) == 7
    assert seen["in_flight"] is True and seen["phase"] == "device"
    assert heartbeat.snapshot()["in_flight"] is False


def test_watchdog_hang_trigger_fires_exit4_with_relay_verdict(
        monkeypatch, capsys):
    """The tentpole contract: relay probe says ALIVE every cycle
    (stalled relay / wedged lease look exactly like this), the guarded
    region goes stale past its deadline -> exit 4 with the port
    verdict attached to the report."""
    from tpu_reductions.utils.watchdog import start_relay_watchdog

    monkeypatch.setenv("TPU_REDUCTIONS_HEARTBEAT_DEADLINE_S", "0.05")
    fired = threading.Event()
    codes = []

    def fake_exit(code):
        codes.append(code)
        fired.set()

    stop = start_relay_watchdog(interval_s=0.02, grace=3,
                                _probe=lambda: "alive", _exit=fake_exit)
    assert stop is not None
    try:
        # no guard open: several cycles pass without firing
        time.sleep(0.2)
        assert not fired.is_set()
        with heartbeat.guard("device"):
            assert fired.wait(timeout=5.0)
    finally:
        stop.set()
    assert codes[0] == HANG_EXIT_CODE
    err = capsys.readouterr().err
    assert "HANG" in err
    assert "verdict at fire time: alive" in err


def test_watchdog_hang_trigger_respects_compile_deadline(monkeypatch):
    """A compile-phase guard tolerates the long deadline (the 20-40 s
    first-Pallas-compile budget): with steady compressed to 50 ms but
    compile left at 30 s, a stale compile guard must NOT fire."""
    from tpu_reductions.utils.watchdog import start_relay_watchdog

    monkeypatch.setenv("TPU_REDUCTIONS_HEARTBEAT_DEADLINE_S", "0.05")
    monkeypatch.setenv("TPU_REDUCTIONS_HEARTBEAT_COMPILE_DEADLINE_S", "30")
    fired = threading.Event()
    stop = start_relay_watchdog(interval_s=0.02, grace=3,
                                _probe=lambda: "alive",
                                _exit=lambda c: fired.set())
    assert stop is not None
    try:
        with heartbeat.guard(heartbeat.PHASE_COMPILE):
            time.sleep(0.3)
            assert not fired.is_set()
    finally:
        stop.set()


def test_hang_trigger_disabled_by_nonpositive_deadline(monkeypatch):
    monkeypatch.setenv("TPU_REDUCTIONS_HEARTBEAT_DEADLINE_S", "0")
    from tpu_reductions.utils.watchdog import _check_hang

    with heartbeat.guard("device"):
        time.sleep(0.05)
        _check_hang("alive", None,
                    lambda c: (_ for _ in ()).throw(
                        AssertionError("fired with trigger disabled")))
