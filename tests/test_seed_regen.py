"""The spot->flagship-cache bridge (bench/seed_cache.py) and the
offline report regenerator (bench/regen.py): on a flapping relay the
session's spot scoreboards may be the only fresh measurements a window
lands, and these two tools are what carry them into the committed
report (examples/tpu_run) without the 3 h flagship step. Gate: only
rows measured at EXACTLY the flagship contract move (sweep.cell_matches
— the same acceptance test sweep_all resume uses), live cells are
never overwritten, re-seeding is a no-op, and regen prefers contract
rows while falling back honestly to legacy ones."""

import json
from pathlib import Path

from tpu_reductions.bench.regen import collect_averages, regenerate
from tpu_reductions.bench.seed_cache import seed
from tpu_reductions.bench.sweep import FLAGSHIP_GRID, cell_matches

CONTRACT = {k: FLAGSHIP_GRID[k] for k in
            ("n", "backend", "kernel", "threads", "iterations",
             "timing", "chain_reps")}


def _grid_row(method="SUM", dtype="float64", gbps=150.0, **over):
    row = {"method": method, "dtype": dtype, "n": FLAGSHIP_GRID["n"],
           "backend": "pallas", "kernel": FLAGSHIP_GRID["kernel"],
           "gbps": gbps, "avg_s": 1e-3,
           "iterations": FLAGSHIP_GRID["iterations"],
           "status": "PASSED", "device_result": 1.0,
           "oracle_result": 1.0, "abs_diff": 0.0, "waived_reason": None,
           "timing": FLAGSHIP_GRID["timing"],
           "threads": FLAGSHIP_GRID["threads"], "max_blocks": 64,
           "chain_reps": FLAGSHIP_GRID["chain_reps"]}
    row.update(over)
    return row


def _legacy_row(method="SUM", dtype="float64", gbps=0.87):
    """A round-2-shaped f64 cell: fetch discipline, no chain_reps —
    exactly what examples/tpu_run/single_chip holds today."""
    r = _grid_row(method, dtype, gbps, timing="fetch")
    del r["chain_reps"], r["max_blocks"]
    return r


def _spot_artifact(path: Path, rows):
    path.write_text(json.dumps(
        {"dtype": "DOUBLE", "n": FLAGSHIP_GRID["n"], "complete": True,
         "rows": rows}))
    return path


def test_cell_matches_discriminates():
    ok = _grid_row()
    assert cell_matches(ok, method="SUM", dtype="float64", **CONTRACT)
    assert not cell_matches(_legacy_row(), method="SUM",
                            dtype="float64", **CONTRACT)
    for bad in (_grid_row(status="FAILED"),
                _grid_row(chain_reps=7),
                _grid_row(threads=384),
                _grid_row(kernel=7),
                _grid_row(n=1 << 20),
                _grid_row(iterations=128)):
        assert not cell_matches(bad, method="SUM", dtype="float64",
                                **CONTRACT)
    # method/dtype mismatch: a MIN row must not fill a SUM slot
    assert not cell_matches(_grid_row(method="MIN"), method="SUM",
                            dtype="float64", **CONTRACT)


def test_seed_replaces_stale_never_live(tmp_path):
    raw = tmp_path / "grid" / "raw_output"
    raw.mkdir(parents=True)
    # slot 0: stale legacy cell; slot 1: live contract cell
    (raw / "run-float64-SUM-0.json").write_text(
        json.dumps(_legacy_row()))
    live = _grid_row(gbps=140.0)
    (raw / "run-float64-SUM-1.json").write_text(json.dumps(live))

    fresh = _grid_row(gbps=150.0)
    spot = _spot_artifact(tmp_path / "spot.json", [fresh])
    seeded = seed(spot, tmp_path / "grid", log=lambda *a: None)
    assert [p.name for p in seeded] == ["run-float64-SUM-0.json"]
    got = json.loads((raw / "run-float64-SUM-0.json").read_text())
    assert got["gbps"] == 150.0 and got["repeat"] == 0
    assert got["seeded_from"] == "spot.json"
    # the live cell was untouched
    assert json.loads((raw / "run-float64-SUM-1.json").read_text()) \
        == live
    # idempotent: the same measurement never seeds twice
    assert seed(spot, tmp_path / "grid", log=lambda *a: None) == []


def test_seed_skips_nonmatching_rows(tmp_path):
    spot = _spot_artifact(tmp_path / "spot.json",
                          [_grid_row(kernel=7, threads=384),
                           _legacy_row(),
                           _grid_row(dtype="bfloat16")])
    assert seed(spot, tmp_path / "grid", log=lambda *a: None) == []


def test_collect_averages_prefers_contract_rows(tmp_path):
    raw = tmp_path / "raw_output"
    raw.mkdir(parents=True)
    (raw / "run-float64-SUM-0.json").write_text(
        json.dumps(_grid_row(gbps=150.0)))
    (raw / "run-float64-SUM-1.json").write_text(
        json.dumps(_legacy_row(gbps=0.87)))   # ignored: contract exists
    (raw / "run-float64-MIN-0.json").write_text(
        json.dumps(_legacy_row("MIN", gbps=0.89)))  # legacy fallback
    (raw / "run-int32-SUM-0.json").write_text(
        json.dumps(_grid_row("SUM", "int32", gbps=6000.0)))
    avgs = collect_averages(tmp_path, log=lambda *a: None)
    assert avgs[("DOUBLE", "SUM")] == 150.0
    assert avgs[("DOUBLE", "MIN")] == 0.89
    assert avgs[("INT", "SUM")] == 6000.0


def test_regenerate_end_to_end(tmp_path):
    out = tmp_path / "exp"
    raw = out / "single_chip" / "raw_output"
    raw.mkdir(parents=True)
    (raw / "run-float64-SUM-0.json").write_text(
        json.dumps(_grid_row(gbps=150.0)))
    (out / "shmoo.json").write_text(json.dumps(
        [_grid_row("SUM", "int32", gbps=500.0, n=1 << 20)]))
    (out / "calibration.json").write_text(json.dumps(
        {"platform": "tpu", "n": 1 << 26,
         "block_awaits_execution": False}))
    assert regenerate(out, log=lambda *a: None) is True
    assert (out / "report.md").exists()
    avgs = json.loads(
        (out / "single_chip" / "averages.json").read_text())
    assert avgs["DOUBLE SUM"] == 150.0
    md = (out / "report.md").read_text()
    assert "150.0" in md or "150." in md

    # an empty dir is a clean no-op
    assert regenerate(tmp_path / "nothing", log=lambda *a: None) is False


def test_seed_skips_nonfinite_gbps_rows(tmp_path):
    """Round-4 ADVICE 3: a PASSED row whose gbps serialized as null
    (non-finite rates nullify in to_dict) must be skipped — it would
    crash the seeder's own log line mid-batch and later the sweep
    resume log — and must not abort the remaining rows' seeding."""
    spot = _spot_artifact(tmp_path / "s.json",
                          [_grid_row("SUM", gbps=None),
                           _grid_row("MIN", gbps=151.0)])
    logs = []
    seeded = seed(spot, tmp_path / "grid", log=logs.append)
    names = [p.name for p in seeded]
    assert names == ["run-float64-MIN-0.json"]
    assert any("non-finite gbps; skipped" in l for l in logs)


def test_collect_averages_legacy_pins_threads_and_backend(tmp_path):
    """Round-4 ADVICE 2: the legacy fallback accepts only the FULL
    flagship geometry — a stray PASSED race cell at threads=1024 (or an
    xla comparator row) in raw_output must never be averaged into the
    flagship table when no contract rows exist."""
    raw = tmp_path / "raw_output"
    raw.mkdir()
    # intended legacy: round-2 f64 fetch row at threads=512/pallas
    (raw / "run-float64-SUM-0.json").write_text(
        json.dumps(_legacy_row("SUM", gbps=0.87)))
    # interlopers at the same n/kernel but wrong threads / backend
    stray1 = _legacy_row("SUM", gbps=9999.0)
    stray1["threads"] = 1024
    (raw / "run-float64-SUM-1.json").write_text(json.dumps(stray1))
    stray2 = _legacy_row("SUM", gbps=8888.0)
    stray2["backend"] = "xla"
    (raw / "run-float64-SUM-2.json").write_text(json.dumps(stray2))
    avgs = collect_averages(tmp_path, log=lambda *a: None)
    assert avgs[("DOUBLE", "SUM")] == 0.87


def test_regenerate_folds_stream_and_compile_tables(tmp_path):
    """ISSUE 8: the committed stream probes (relocated into the
    experiment dir) and the compile observatory's per-surface table
    fold into report.md next to the GB/s tables."""
    out = tmp_path / "exp"
    raw = out / "single_chip" / "raw_output"
    raw.mkdir(parents=True)
    (raw / "run-float64-SUM-0.json").write_text(
        json.dumps(_grid_row(gbps=150.0)))
    (out / "stream_probe.json").write_text(json.dumps({
        "mode": "stream", "method": "SUM", "dtype": "int32",
        "n": 1 << 26, "complete": True,
        "rows": [{"final": True, "num_chunks": 16,
                  "gbps_sustained": 12.5, "chunks_per_s": 3.1,
                  "overlap_efficiency": 1.4, "status": "PASSED"}]}))
    (out / "compile_ledger.json").write_text(json.dumps({
        "kind": "compile-observatory", "version": 1, "complete": True,
        "surfaces": [{"surface": "k10@4", "platform": "tpu",
                      "verdict": "cold", "dur_s": 33.2, "count": 1}]}))
    assert regenerate(out, log=lambda *a: None) is True
    md = (out / "report.md").read_text()
    assert "streaming pipeline (committed probes)" in md
    assert "| stream_probe | SUM/int32 |" in md and "x1.4" in md
    assert "compile observatory (per-surface cold/warm)" in md
    assert "k10@4" in md
